"""Compile- and correctness-check every Pallas kernel on the real TPU.

Small shapes: fast compiles, exact or tolerance checks vs the XLA paths.
Exit 0 = all kernels lower under Mosaic and agree with the reference paths.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root

import jax
import jax.numpy as jnp
import numpy as np

failures = []


def check(name, fn):
    try:
        fn()
        print(f"{name}: OK", flush=True)
    except Exception as e:
        msg = str(e).split("\n")[0][:200]
        failures.append(name)
        print(f"{name}: FAILED {type(e).__name__}: {msg}", flush=True)


def stencils():
    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops import run_heat
    from cme213_tpu.ops.stencil_pallas import (run_heat_multistep,
                                               run_heat_pallas)

    for order in (2, 4, 8):
        p = SimParams(nx=256, ny=256, order=order, iters=8)
        u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
        ref = np.asarray(run_heat(jnp.array(u0), 8, order, p.xcfl, p.ycfl))

        def one(order=order, p=p, u0=u0, ref=ref):
            out = np.asarray(run_heat_pallas(
                jnp.array(u0), 8, order, p.xcfl, p.ycfl, tile_y=64))
            assert np.array_equal(out, ref), np.abs(out - ref).max()

        def multi(order=order, p=p, u0=u0, ref=ref):
            for k in (2, 4, 8):
                out = np.asarray(run_heat_multistep(
                    jnp.array(u0), 8, order, p.xcfl, p.ycfl, p.bc,
                    k=k, tile_y=64))
                assert np.array_equal(out, ref), (k, np.abs(out - ref).max())

        check(f"stencil-pallas order={order}", one)
        check(f"stencil-multistep order={order}", multi)

    from cme213_tpu.ops.stencil_pipeline import (run_heat_pipeline,
                                                 run_heat_pipeline2d)

    for order in (2, 4, 8):
        p = SimParams(nx=256, ny=256, order=order, iters=8)
        u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
        ref = np.asarray(run_heat(jnp.array(u0), 8, order, p.xcfl, p.ycfl))

        def pipe(order=order, p=p, u0=u0, ref=ref):
            for k in (1, 2, 4):
                out = np.asarray(run_heat_pipeline(
                    jnp.array(u0), 8, order, p.xcfl, p.ycfl, p.bc,
                    k=k, tile_y=64))
                assert np.array_equal(out, ref), (k, np.abs(out - ref).max())

        def pipe2d(order=order, p=p, u0=u0, ref=ref):
            for k in (1, 4):
                out = np.asarray(run_heat_pipeline2d(
                    jnp.array(u0), 8, order, p.xcfl, p.ycfl, p.bc,
                    k=k, tile_y=64, tile_x=128))
                assert np.array_equal(out, ref), (k, np.abs(out - ref).max())

        check(f"stencil-pipeline order={order}", pipe)
        check(f"stencil-pipeline2d order={order}", pipe2d)


def segscan():
    from cme213_tpu.ops.segmented import (head_flags_from_starts,
                                          segmented_scan)
    from cme213_tpu.ops.segmented_pallas import (segmented_scan_pallas,
                                                 spmv_scan_pallas)

    rng = np.random.default_rng(0)
    n = 10_000
    v = rng.standard_normal(n).astype(np.float32)
    starts = np.unique(rng.integers(1, n, 37))
    starts = np.concatenate([[0], starts]).astype(np.int32)
    flags = head_flags_from_starts(jnp.asarray(starts), n)
    ref = np.asarray(segmented_scan(jnp.asarray(v), flags))

    def scan():
        out = np.asarray(segmented_scan_pallas(jnp.asarray(v), flags))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-4)

    def fused():
        xx = rng.uniform(0.5, 1.5, n).astype(np.float32)
        from cme213_tpu.ops.segmented import segmented_scan as ss
        a = jnp.asarray(v)
        ref2 = a
        for _ in range(3):
            ref2 = ss(ref2 * jnp.asarray(xx), flags)
        out = np.asarray(spmv_scan_pallas(jnp.asarray(v), jnp.asarray(xx),
                                          flags, 3))
        np.testing.assert_allclose(out, np.asarray(ref2), rtol=2e-4,
                                   atol=2e-3)

    check("segmented-scan-pallas", scan)
    check("spmv-scan-pallas fused", fused)


def transpose():
    from cme213_tpu.ops.transpose import transpose_pallas

    rng = np.random.default_rng(1)
    x = rng.standard_normal((512, 256)).astype(np.float32)

    def run():
        out = np.asarray(transpose_pallas(jnp.asarray(x), tile=256))
        assert np.array_equal(out, x.T)

    check("transpose-pallas", run)


if __name__ == "__main__":
    print("device:", jax.devices()[0], flush=True)
    stencils()
    segscan()
    transpose()
    if failures:
        print("FAILURES:", failures)
        sys.exit(1)
    print("ALL PALLAS KERNELS OK")
