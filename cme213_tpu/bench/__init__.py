from .sweeps import (
    cipher_vector_length_sweep,
    dist_heat_sweep,
    pagerank_avg_edges_sweep,
    heat_sweep,
    scan_sweep,
    pallas_tile_sweep,
    sort_thread_sweep,
    spmv_scan_sweep,
    spmv_suite_sweep,
    transfer_bandwidth_sweep,
    write_csv,
)

__all__ = [
    "cipher_vector_length_sweep",
    "dist_heat_sweep",
    "scan_sweep",
    "pagerank_avg_edges_sweep",
    "heat_sweep",
    "pallas_tile_sweep",
    "sort_thread_sweep",
    "spmv_scan_sweep",
    "spmv_suite_sweep",
    "transfer_bandwidth_sweep",
    "write_csv",
]
