"""In-process metrics registry — named counters, gauges, histograms.

The reference derived every metric offline, in spreadsheets over printed
timer lines (SURVEY §5); a production system pulls named metrics from the
process instead (the Prometheus model).  This registry is that pull
surface, deliberately tiny: no label sets, no exposition server — just
named instruments a solver increments on its host path and a
``snapshot()`` the bench harness (``bench/run_all.py``) and the trace
sink (a ``metrics-snapshot`` event at exit) serialize::

    from cme213_tpu.core import metrics
    metrics.counter("fallback.demotions").inc()
    metrics.histogram("commit.ms").observe(12.3)
    metrics.gauge("gang.world").set(4)

Instruments are created on first use and process-global; snapshotting is
lock-consistent.  Histograms keep a bounded ring of recent observations
(``KEEP`` = 4096) for percentiles plus exact count/sum — a long solve
cannot grow memory without bound.  Everything here is host-side dict and
deque work: effectively free next to any device work it measures, and
exactly zero when never called.

``delta(before, after)`` diffs two snapshots (counter/histogram-count
deltas, latest gauge values) — what ``run_all`` attaches to each sweep's
row set in ``metrics.json``.
"""

from __future__ import annotations

import atexit
import threading
from collections import deque

#: observations retained per histogram for percentile estimates
KEEP = 4096

_LOCK = threading.Lock()
_COUNTERS: dict[str, "Counter"] = {}
_GAUGES: dict[str, "Gauge"] = {}
_HISTOGRAMS: dict[str, "Histogram"] = {}


class Counter:
    """Monotonic named count (demotions, retries, commits, faults)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> "Counter":
        with _LOCK:
            self.value += n
        return self


class Gauge:
    """Last-write-wins named value (world size, live epoch, ...)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = None

    def set(self, value) -> "Gauge":
        with _LOCK:
            self.value = value
        return self


class Histogram:
    """Named distribution: exact count/sum/min/max plus percentiles over
    the last ``KEEP`` observations (a ring — bounded by construction)."""

    __slots__ = ("name", "count", "total", "min", "max", "_recent")

    def __init__(self, name: str) -> None:
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._recent: deque = deque(maxlen=KEEP)

    def observe(self, value: float) -> "Histogram":
        value = float(value)
        with _LOCK:
            self.count += 1
            self.total += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            self._recent.append(value)
        return self

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile (q in [0, 1]) over retained
        observations; None when empty."""
        with _LOCK:
            vals = sorted(self._recent)
        if not vals:
            return None
        idx = min(len(vals) - 1, max(0, round(q * (len(vals) - 1))))
        return vals[idx]

    def _summary_locked(self) -> dict:
        vals = sorted(self._recent)

        def pct(q):
            if not vals:
                return None
            return vals[min(len(vals) - 1, max(0, round(q * (len(vals) - 1))))]

        return {
            "count": self.count,
            "sum": round(self.total, 6),
            "mean": round(self.total / self.count, 6) if self.count else None,
            "min": self.min,
            "max": self.max,
            "p50": pct(0.50),
            "p90": pct(0.90),
            "p99": pct(0.99),
        }


def counter(name: str) -> Counter:
    with _LOCK:
        c = _COUNTERS.get(name)
        if c is None:
            c = _COUNTERS[name] = Counter(name)
    return c


def gauge(name: str) -> Gauge:
    with _LOCK:
        g = _GAUGES.get(name)
        if g is None:
            g = _GAUGES[name] = Gauge(name)
    return g


def histogram(name: str) -> Histogram:
    with _LOCK:
        h = _HISTOGRAMS.get(name)
        if h is None:
            h = _HISTOGRAMS[name] = Histogram(name)
    return h


def snapshot() -> dict:
    """Lock-consistent ``{counters, gauges, histograms}`` view of the
    registry — JSON-serializable (what trace files and bench artifacts
    embed)."""
    with _LOCK:
        return {
            "counters": {k: c.value for k, c in sorted(_COUNTERS.items())},
            "gauges": {k: g.value for k, g in sorted(_GAUGES.items())},
            "histograms": {k: h._summary_locked()
                           for k, h in sorted(_HISTOGRAMS.items())},
        }


def delta(before: dict, after: dict) -> dict:
    """What changed between two snapshots: nonzero counter deltas, gauges
    at their ``after`` values, histograms that saw new observations (with
    their ``after`` percentiles — percentiles don't subtract)."""
    counters = {}
    for k, v in after.get("counters", {}).items():
        d = v - before.get("counters", {}).get(k, 0)
        if d:
            counters[k] = d
    histograms = {}
    for k, h in after.get("histograms", {}).items():
        d = h["count"] - before.get("histograms", {}).get(k, {}).get("count", 0)
        if d:
            histograms[k] = {**h, "count_delta": d}
    return {"counters": counters,
            "gauges": dict(after.get("gauges", {})),
            "histograms": histograms}


def reset() -> None:
    """Forget every instrument (tests)."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTOGRAMS.clear()


def _emit_exit_snapshot() -> None:
    """At interpreter exit, append one ``metrics-snapshot`` event so sink
    files end with the process's final registry state.  Skipped when the
    registry was never touched (no instruments -> no record)."""
    if not (_COUNTERS or _GAUGES or _HISTOGRAMS):
        return
    from .trace import flush_sink, record_event

    record_event("metrics-snapshot", metrics=snapshot())
    flush_sink()


atexit.register(_emit_exit_snapshot)
