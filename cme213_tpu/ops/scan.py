"""Prefix scans — library-algorithm parallelism (strategy P5/P7).

The reference uses scans in two shapes: a serial exclusive scan over radix
buckets (``hw/hw4/programming/radixsort.cpp:75-108``) and the
block-decomposed upsweep/scan/downsweep pattern (per-block partials → global
scan → per-block bases) in the parallel radix sort — the classic
Blelloch/Sengupta structure (``my-refs/scan.pdf``).  On TPU the flat scan is
``jax.lax.associative_scan`` (log-depth, XLA-fused); the *blocked* scan is
kept as a first-class shape because it is exactly the multi-device scan story
(per-shard scan + carry exchange, see ``dist/scan.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def inclusive_scan(x: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    # lax.cumsum has a dedicated fast lowering (associative_scan's generic
    # slice-recursion compiles pathologically slowly for ragged sizes)
    return lax.cumsum(x, axis=axis)


def exclusive_scan(x: jnp.ndarray, axis: int = 0) -> jnp.ndarray:
    """Exclusive sum scan (identity first), as the radix bucket scan
    (radixsort.cpp:75-83)."""
    zero_shape = list(x.shape)
    zero_shape[axis] = 1
    zero = jnp.zeros(zero_shape, x.dtype)
    shifted = lax.concatenate(
        [zero, lax.slice_in_dim(x, 0, x.shape[axis] - 1, axis=axis)],
        dimension=axis,
    )
    return lax.cumsum(shifted, axis=axis)


def blocked_inclusive_scan(x: jnp.ndarray, block_size: int) -> jnp.ndarray:
    """Inclusive scan via the 3-phase block decomposition.

    Phase structure mirrors the reference radix pass (radixsort.cpp:44-108):
    (1) per-block local scans, (2) scan of block totals, (3) broadcast-add of
    block bases.  Requires ``len(x) % block_size == 0`` (drivers pad).
    """
    n = x.shape[0]
    assert n % block_size == 0, "pad to a multiple of block_size"
    blocks = x.reshape(n // block_size, block_size)
    local = lax.cumsum(blocks, axis=1)
    totals = local[:, -1]
    bases = exclusive_scan(totals)
    return (local + bases[:, None]).reshape(n)
