import numpy as np
import pytest

from cme213_tpu.apps import spmv_scan as sp
from cme213_tpu.verify import golden


def test_generate_and_validate():
    prob = sp.generate_problem(1000, 40, 128, iters=7, seed=1)
    prob.validate()
    assert prob.n == 1000 and prob.p == 40 and prob.q == 128
    assert prob.s[0] == 0 and prob.s[-1] == 1000


def test_file_roundtrip(tmp_path):
    prob = sp.generate_problem(200, 10, 32, iters=3, seed=2)
    a, x = str(tmp_path / "a.txt"), str(tmp_path / "x.txt")
    sp.save_problem(prob, a, x)
    loaded = sp.load_problem(a, x)
    np.testing.assert_allclose(loaded.a, prob.a, rtol=1e-6)
    np.testing.assert_array_equal(loaded.s, prob.s)
    np.testing.assert_array_equal(loaded.k, prob.k)
    np.testing.assert_allclose(loaded.x, prob.x, rtol=1e-6)
    assert loaded.iters == prob.iters


def test_validate_rejects_bad_segments():
    prob = sp.generate_problem(100, 8, 16, iters=2)
    prob.s[-1] = 99  # break the end sentinel
    with pytest.raises(ValueError):
        prob.validate()


def test_matches_cpu_golden_small():
    prob = sp.generate_problem(500, 20, 64, iters=5, seed=3)
    out = sp.run_spmv_scan(prob)
    ref = golden.host_spmv_scan(prob.a, prob.s[:-1], prob.xx, prob.iters)
    # accumulating float pipeline: reference uses abs tol 1e-2 (fp.cu:193)
    np.testing.assert_allclose(out, ref, atol=1e-2)


def test_external_double_checker():
    prob = sp.generate_problem(2000, 100, 256, iters=10, seed=4)
    out = sp.run_spmv_scan(prob)
    errs = sp.external_check(prob, out)
    # accuracy bar from the reference report: rel L2/L∞ < 1e-6..1e-3
    assert errs["rel_l2"] < 1e-3
    assert errs["rel_linf"] < 1e-3


def test_single_element_segments():
    # s = [0,1,2,...,n] → every segment length 1 → scan is identity,
    # result = a · xx^iters
    n = 64
    prob = sp.generate_problem(n, n + 1, 8, iters=3, seed=5)
    prob.s = np.arange(n + 1, dtype=np.int32)
    prob.validate()
    out = sp.run_spmv_scan(prob)
    ref = prob.a * prob.xx**3
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_suite_problem_scaled():
    prob = sp.suite_problem("jonheart", scale=0.01)
    prob.validate()
    out = sp.run_spmv_scan(prob)
    assert np.isfinite(out).all()


def test_native_loader_matches_python(tmp_path):
    prob = sp.generate_problem(2000, 40, 37, iters=7, seed=3)
    a, x = str(tmp_path / "a.txt"), str(tmp_path / "x.txt")
    sp.save_problem(prob, a, x)
    py = sp.load_problem(a, x, use_native=False)
    nat = sp.load_problem(a, x, use_native=True)
    np.testing.assert_array_equal(py.a, nat.a)
    np.testing.assert_array_equal(py.s, nat.s)
    np.testing.assert_array_equal(py.k, nat.k)
    np.testing.assert_array_equal(py.x, nat.x)
    assert py.iters == nat.iters


def test_native_write_read_floats(tmp_path):
    from cme213_tpu import native

    rng = np.random.default_rng(5)
    vals = rng.standard_normal(777).astype(np.float32)
    path = str(tmp_path / "b.txt")
    native.write_floats(path, vals)
    back = native.read_floats(path, 777)
    np.testing.assert_array_equal(vals, back)  # %.9g round-trips f32
    with pytest.raises(ValueError):
        native.read_floats(path, 778)


def test_cli_run_and_check(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    assert sp.main(["spmv_scan", "gen", "a.txt", "x.txt",
                    "3000", "50", "49", "6"]) == 0
    assert sp.main(["spmv_scan", "a.txt", "x.txt", "cpu_check"]) == 0
    cap = capsys.readouterr().out
    assert "The running time of my code for 6 iterations is:" in cap
    assert "Worked! device and reference output match." in cap
    b = np.loadtxt("b.txt", dtype=np.float32)
    b_cpu = np.loadtxt("b_cpu.txt", dtype=np.float32)
    assert b.shape == b_cpu.shape == (3000,)
    scale = np.abs(b_cpu).max()
    assert np.abs(b - b_cpu).max() <= 1e-3 * max(scale, 1.0)
    assert sp.main(["spmv_scan", "nope.txt", "x.txt"]) == 2


def test_dense_kernel_matches_flat():
    prob = sp.generate_problem(4000, 80, 79, iters=4, seed=9)
    out_flat = sp.run_spmv_scan(prob, kernel="flat")
    out_dense = sp.run_spmv_scan(prob, kernel="dense")
    scale = max(1.0, float(np.abs(out_flat).max()))
    np.testing.assert_allclose(out_dense, out_flat, rtol=1e-5,
                               atol=1e-6 * scale)


def test_native_loader_malformed_falls_back_or_raises(tmp_path):
    from cme213_tpu import native

    bad = tmp_path / "bad.txt"
    bad.write_text("3 2 2\n")  # truncated header (3 of 4 ints)
    with pytest.raises((OSError, ValueError)):
        native.spmv_read(str(bad))

    short = tmp_path / "short.txt"
    short.write_text("4 2 2 5\n1.0 2.0\n")  # promises 4 values, has 2
    with pytest.raises(ValueError):
        native.spmv_read(str(short))
