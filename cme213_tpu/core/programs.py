"""Process-wide program cache — compile once per shape class, serve hits.

The reference's CUDA workloads load their module once and serve every
launch from it; our dispatch historically rebuilt fresh jit closures per
call, so every solve re-entered the trace/compile path — exactly what
the retrace detector (``core/trace._note_compile_run``, ROADMAP item 5's
measurement half) counts.  This module is the amortization half: a
process-wide cache of **warmed** callables keyed by

    (op, rung, shape_class, dtype, static params)

Dispatch (``apps/spmv_scan.py``, ``ops/stencil_pipeline.py``,
``apps/heat2d.py``), the serving batch runners (``serve/workloads.py``),
and the conformance-gate probes all fetch their programs through
:func:`get`:

- **hit**: one dict lookup returns the already-warmed callable — no
  compile span opens, no warmup runs, the retrace detector sees nothing
  (``program-cache-hit`` event, ``programs.hits`` counter);
- **miss**: ``build()`` runs inside an ``<op>.compile`` span (feeding the
  ``compile.<op>.<class>.ms`` histogram and the retrace detector), then
  ``warm(fn)`` executes the program once behind the caller's named
  barrier before the entry is published (``program-cache-miss`` event,
  ``programs.misses`` counter).  A build or warmup that raises caches
  nothing — a rung that failed to compile is a demotion, not a program.

Cached callables must take every per-problem array as an **argument**
(values, gathered x, head flags, grids) — closing over request data
would serve one caller's inputs to another.  Anything that changes the
compiled program (iteration count, tile size, CFL constants, batch
width) goes into the key via ``**static``.

:func:`canonical_size` is the pad-and-mask companion: it snaps request
sizes to power-of-two buckets so heterogeneous traffic lands on a small
set of shape classes (the T5X canonical-shapes discipline), which is
what makes a per-class cache finite under real load.

``reset()`` clears the cache (tests; also invoked by
``trace.clear_events`` so compile counts and cached programs move
together).
"""

from __future__ import annotations

import threading

from . import diag, metrics
from .faults import maybe_fail_stage
from .trace import record_event, span

_LOCK = threading.RLock()
_CACHE: dict[tuple, object] = {}


def canonical_size(n: int, floor: int = 1) -> int:
    """The canonical shape bucket for a size-``n`` request: the next
    power of two (>= ``floor``).  Generalizes the coarse buckets that
    used to exist only in degraded serving mode — padding requests up to
    a bucket (``apps.spmv_scan.pad_problem``'s quarantined tail) trades
    O(n) zero-padded work for a bounded set of compiled programs."""
    n = max(int(n), int(floor))
    return 1 << max(0, (n - 1)).bit_length()


def _key(op: str, rung: str, shape_class: str, dtype, static: dict) -> tuple:
    return (op, str(rung), str(shape_class), str(dtype),
            tuple(sorted((k, repr(v)) for k, v in static.items())))


def get(op: str, rung: str, shape_class: str, build, *, dtype="f32",
        warm=None, cost=None, probe=None, **static):
    """The process-wide program for ``(op, rung, shape_class, dtype,
    static)`` — built, warmed, and cached on first use; a dict lookup
    ever after.

    ``build()`` returns the callable; ``warm(fn)`` (optional) executes it
    once so XLA compiles outside any timed region — both run inside the
    ``<op>.compile`` span on a miss, so the compile/run split and the
    retrace detector keep measuring exactly what they did before, and a
    second call on a known shape class measurably does *nothing*.

    Forensics (``core/diag.py``): ``build()`` runs under the ``lower``
    stage scope and ``warm(fn)`` under ``compile``, so an exception out of
    a miss is attributed to the phase that actually died (a Mosaic error
    escaping warmup is refined back to ``lower`` by message).  Attribution
    (opt-in via ``CME213_DIAG_ATTRIBUTION``): pass the roofline ``cost``
    and a zero-arg ``probe`` returning example args and a fresh program is
    cross-checked against ``compiled.cost_analysis()`` right after it is
    cached — the point where one extra lowering is cheapest.
    """
    key = _key(op, rung, shape_class, dtype, static)
    with _LOCK:
        fn = _CACHE.get(key)
    if fn is not None:
        record_event("program-cache-hit", op=op, rung=rung,
                     shape_class=shape_class)
        metrics.counter("programs.hits").inc()
        return fn
    record_event("program-cache-miss", op=op, rung=rung,
                 shape_class=shape_class)
    metrics.counter("programs.misses").inc()
    with span(f"{op}.compile", kernel=rung, shape_class=shape_class):
        maybe_fail_stage(f"{op}.{rung}", "lower")
        with diag.stage_scope(f"{op}.{rung}", "lower"):
            fn = build()
        if warm is not None:
            maybe_fail_stage(f"{op}.{rung}", "compile")
            with diag.stage_scope(f"{op}.{rung}", "compile"):
                warm(fn)
    with _LOCK:
        _CACHE[key] = fn
    diag.maybe_check_attribution(op, rung, shape_class, fn, probe, cost)
    return fn


def size() -> int:
    """Number of cached programs."""
    with _LOCK:
        return len(_CACHE)


def keys() -> list[tuple]:
    """Snapshot of cache keys (introspection/tests)."""
    with _LOCK:
        return sorted(_CACHE)


def reset() -> None:
    """Forget every cached program (tests; paired with
    ``trace.clear_events`` so a fresh telemetry slate implies a cold
    cache)."""
    with _LOCK:
        _CACHE.clear()
