from .timing import PhaseTimer, bandwidth_gbs, gflops
from .compare import ulp_distance, almost_equal_ulps
from .errors import DataValidationError, check_op, FrameworkError
from .resilience import (FailureKind, FallbackResult, NonFiniteError,
                         RetryPolicy, all_finite, classify_failure,
                         with_fallback)
from .trace import (EVENT_SCHEMA, clear_events, events, flush_sink,
                    record_event, span, validate_record)
from . import admission, conformance, diag, metrics, programs, roofline

__all__ = [
    "PhaseTimer",
    "bandwidth_gbs",
    "gflops",
    "ulp_distance",
    "almost_equal_ulps",
    "check_op",
    "DataValidationError",
    "FrameworkError",
    "FailureKind",
    "FallbackResult",
    "NonFiniteError",
    "RetryPolicy",
    "all_finite",
    "classify_failure",
    "with_fallback",
    "record_event",
    "events",
    "clear_events",
    "span",
    "flush_sink",
    "validate_record",
    "EVENT_SCHEMA",
    "admission",
    "conformance",
    "diag",
    "metrics",
    "programs",
    "roofline",
]
