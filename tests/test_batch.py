"""Batch job runner (bench/batch.py) — the PBS/qsub layer.

Reference model: ``hw/hw4/programming/pa4.pbs`` (OMP_NUM_THREADS sweep with
captured ``.o``/``.e`` logs); parsing/sweep semantics are ours.
"""

import os

import pytest

from cme213_tpu.bench.batch import JobSpec, main, parse_job, run_job


def _write(tmp_path, text, name="j.job"):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_parse_directives(tmp_path):
    path = _write(tmp_path, (
        "#CME name=myjob\n"
        "#CME out=some/dir\n"
        "#CME timeout=12.5\n"
        "#CME sweep A=1,2\n"
        "#CME sweep B=x,y,z\n"
        "echo hello\n"))
    spec = parse_job(path)
    assert spec.name == "myjob"
    assert spec.out == "some/dir"
    assert spec.timeout == 12.5
    assert spec.sweeps == [("A", ["1", "2"]), ("B", ["x", "y", "z"])]
    assert spec.body == "echo hello\n"


def test_points_cartesian_last_axis_fastest(tmp_path):
    spec = JobSpec(name="j", sweeps=[("A", ["1", "2"]), ("B", ["x", "y"])],
                   body="true\n")
    assert spec.points() == [
        {"A": "1", "B": "x"}, {"A": "1", "B": "y"},
        {"A": "2", "B": "x"}, {"A": "2", "B": "y"},
    ]


def test_parse_rejects_bad_directives(tmp_path):
    with pytest.raises(ValueError, match="unknown directive"):
        parse_job(_write(tmp_path, "#CME nodes=2\ntrue\n"))
    with pytest.raises(ValueError, match="bad sweep"):
        parse_job(_write(tmp_path, "#CME sweep =1,2\ntrue\n"))
    with pytest.raises(ValueError, match="unparseable"):
        parse_job(_write(tmp_path, "#CME whatever\ntrue\n"))
    with pytest.raises(ValueError, match="body is empty"):
        parse_job(_write(tmp_path, "#CME name=x\n"))


def test_run_captures_o_e_and_summary(tmp_path):
    out = tmp_path / "logs"
    spec = JobSpec(name="cap", out=str(out), timeout=60,
                   sweeps=[("MYVAR", ["7", "8"])],
                   body="echo val=$MYVAR\necho err=$MYVAR >&2\n")
    rows = run_job(spec)
    assert [r["rc"] for r in rows] == [0, 0]
    assert (out / "cap.o0").read_text() == "val=7\n"
    assert (out / "cap.o1").read_text() == "val=8\n"
    assert (out / "cap.e1").read_text() == "err=8\n"
    summary = (out / "cap.jobs.csv").read_text().splitlines()
    assert summary[0] == "point,MYVAR,rc,seconds"
    assert summary[1].startswith("0,7,0,")


def test_failing_point_recorded_and_exit_nonzero(tmp_path):
    jobfile = _write(tmp_path, (
        "#CME out={out}\n"
        "#CME sweep N=0,3\n"
        "exit $N\n").format(out=tmp_path / "logs"))
    assert main([jobfile]) == 1
    rows = run_job(parse_job(jobfile))
    assert [r["rc"] for r in rows] == [0, 3]


def test_dry_run_writes_nothing(tmp_path, capsys):
    out = tmp_path / "logs"
    jobfile = _write(tmp_path, (
        f"#CME out={out}\n"
        "#CME sweep A=1,2\n"
        "echo run\n"))
    assert main([jobfile, "--dry-run"]) == 0
    assert not out.exists()
    text = capsys.readouterr().out
    assert "A=1" in text and "A=2" in text


def test_shipped_job_specs_parse():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for name in ("sorts_scaling", "heat_ranks", "spmv_scaling"):
        spec = parse_job(os.path.join(repo, "jobs", f"{name}.job"))
        assert spec.name == name
        assert spec.sweeps, name
        assert "python -m cme213_tpu" in spec.body


def test_shipped_jobs_pin_platform_unconditionally():
    """The base image pins JAX_PLATFORMS=axon globally, so a job that sets
    the platform with a ``:-`` default keeps a (possibly dead) tunnel and
    hangs the campaign — any platform export in a shipped job must be an
    unconditional assignment."""
    import glob

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for path in glob.glob(os.path.join(repo, "jobs", "*.job")):
        body = parse_job(path).body
        for line in body.splitlines():
            if "JAX_PLATFORMS" in line and not line.strip().startswith("#"):
                assert ":-" not in line, (path, line)
                assert "JAX_PLATFORMS=" in line, (path, line)
