"""The replicated serving fleet: N server replicas as supervised worker
processes behind one tenant-fair front tier.

This is the serving analog of the reference's hw5 unit — a gang of MPI
ranks cooperating on one workload under supervised relaunch — rebuilt
for request traffic.  It reuses the gang machinery wholesale
(``dist/launch.py`` env conventions: ``JAX_PROCESS_ID`` as the replica
rank, ``CME213_INCARNATION`` bumped per relaunch, ``{rank}``-templated
trace/metrics sinks, heartbeat files from ``dist/supervisor.py``, one
cross-process trace id via ``propagation_env``) but differs in the
failure unit: MPI ranks are a collective, so one death condemns the
gang; replicas are independent, so one death relaunches **that
replica** while the rest keep serving.

Topology::

    clients ── v2 binary frames (or legacy JSON) ──> FleetFrontEnd (this proc)
                                                │  Router (tenant-fair DRR,
                                                │   per-replica breakers,
                                                │   in-flight ledger)
                                     dispatcher │ + one pipelined channel
                                                │   per replica (shm lane
                                                │   when negotiable)
                 ┌──────────────────────────────┼──────────────┐
            replica 0 (proc)               replica 1       ... replica N-1
            Server + TransportServer       (each: warmed program cache,
            (drive="thread", kill_guard)    heartbeats, per-rank sinks)

Dispatch is **pipelined**: each replica is fed over ONE persistent v2
connection carrying up to ``dispatch_width`` requests in flight, keyed
by request id (``serve/wire.py``); payload sections pass through the
front end without re-encoding, and — being same-host — the channel
negotiates the shared-memory lane (``serve/shm.py``) so large payloads
skip the loopback socket entirely.

**Zero accepted-request loss.**  The front end owns every accepted
request until a response exists: a ticket is held in the router's
in-flight ledger while a channel forwards it, and a replica death — seen
as a connection error by the channel (which fails *all* of its in-flight
tickets at once, however deep the pipeline) *and* as a process exit by
the supervisor — requeues the ticket (``request-requeued``) for a healthy
replica.  The dead replica's flight-recorder dump (it dumps before the
injected SIGKILL; see ``faults.maybe_kill_replica``) is read back for
the post-mortem, confirming which requests were mid-batch.  Solves are
pure, so the rare double execution after a mid-response kill is
harmless: the first response wins.

**Autoscaling.**  The front tier runs an ``serve/slo.py`` monitor over
completed responses and a :class:`~.router.Autoscaler` policy tick in
the supervisor loop: sustained ``slo-burn`` spawns the next rank
(``scale-up``), sustained health at low occupancy retires the highest
rank after draining it (``scale-down``), with sustain windows and an
action cooldown for hysteresis — all on the injectable clock.
"""

from __future__ import annotations

import glob
import json
import os
import queue as queue_mod
import subprocess
import sys
import threading
import time

from ..core import flight, metrics
from ..core.faults import KILL_EXIT
from ..core.resilience import Clock
from ..core.trace import (
    propagation_env,
    record_event,
    tail_decide,
    tail_keep_reason,
)
from ..dist.launch import (
    _pump,
    _template_metrics_file,
    _template_trace_file,
    free_port,
)
from ..dist.supervisor import HEARTBEAT_DIR_ENV, HEARTBEAT_INTERVAL_ENV
from . import jobs as jobs_mod
from . import wire
from .request import FAILED, QUEUE_FULL, SHED
from .router import Autoscaler, Router, Ticket
from .transport import (
    RESPONSE_TIMEOUT_S,
    FrameServer,
    TransportClient,
    TransportServer,
)

#: sentinel queued to a sender thread to shut it down
_SENDER_STOP = object()


def _finish_ticket(ticket: Ticket, meta: dict) -> None:
    """Close the front tier's ``serve.hop.route`` span, attach the
    per-hop breakdown to the response meta (``hops`` rides the result
    doc to the client as an extra field: wait/dispatch/requeue residency
    plus the requeue count), and make the front tier's tail-sampling
    keep/drop call — requeues are only visible here, so "kept because
    requeued" is this hop's verdict."""
    hop = ticket.hop
    if hop is None:
        return
    route_ms = hop.end(status=meta.get("status"),
                       requeues=ticket.requeues)
    if route_ms is None:
        return
    hops = dict(ticket.hop_ms)
    hops["route_ms"] = route_ms
    hops["requeues"] = ticket.requeues
    meta["hops"] = hops
    if hop.tail_key is not None:
        reason = tail_keep_reason(status=meta.get("status"),
                                  latency_ms=route_ms,
                                  requeues=ticket.requeues)
        tail_decide(hop.tail_key, keep=reason is not None,
                    reason=reason or "ok")


# ------------------------------------------------------------ replica proc

class ReplicaProc:
    """One supervised replica worker process."""

    def __init__(self, rank: int, incarnation: int, port: int,
                 proc: subprocess.Popen):
        self.rank = rank
        self.incarnation = incarnation
        self.port = port
        self.proc = proc
        self.state = "starting"        # starting | up | down | retired

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"


class ReplicaChannel:
    """One pipelined v2 connection from the front tier to a replica.

    Tickets go out with :meth:`send` (non-blocking past the socket
    write) and complete on the transport client's receiver thread via
    ``_on_response`` — many in flight at once, matched by request id.
    When the connection dies, ``_on_error`` fails **every** in-flight
    ticket back to the router in one sweep: a SIGKILL with a full
    pipeline requeues the whole window through the ledger, losing
    nothing.  Being same-host, the channel asks for the shared-memory
    lane and falls back to the socket when the server declines.
    """

    def __init__(self, fleet: "Fleet", rank: int, addr: str,
                 shm: bool = True, connect_timeout_s: float = 2.0):
        self.fleet = fleet
        self.rank = rank
        self._mu = threading.Lock()
        self._inflight: dict[int, Ticket] = {}
        self._closing = False
        self.dead = False
        self.client = TransportClient(
            addr, connect_timeout_s=connect_timeout_s, shm=shm,
            on_response=self._on_response, on_error=self._on_error)
        # per-peer clock alignment for the request waterfalls: a few
        # ping round trips bound this replica's wall-clock offset
        self.client.sync_clock(samples=3)

    def send(self, ticket: Ticket) -> None:
        """Pipeline one ticket; raises on a dead connection (the caller
        requeues via the router)."""
        rid = self.client.next_rid()
        with self._mu:
            if self.dead:
                raise ConnectionError(f"channel to replica {self.rank} down")
            self._inflight[rid] = ticket
        try:
            self.client.submit_doc(ticket.doc, ticket.sections, rid=rid)
        except Exception:
            with self._mu:
                self._inflight.pop(rid, None)
            raise

    def inflight(self) -> int:
        with self._mu:
            return len(self._inflight)

    # -- receiver-thread callbacks

    def _on_response(self, rid: int, meta: dict, sections: list) -> None:
        with self._mu:
            ticket = self._inflight.pop(rid, None)
        if ticket is None:
            return
        meta.setdefault("replica", self.rank)
        fleet = self.fleet
        with fleet._cv:
            fleet.router.complete(ticket, self.rank)
            fleet._cv.notify_all()
        _finish_ticket(ticket, meta)
        fleet._observe(meta)
        fleet._deliver(ticket, meta, sections)

    def _on_error(self, exc: Exception) -> None:
        with self._mu:
            if self._closing:
                return
            self.dead = True
            pending = list(self._inflight.values())
            self._inflight.clear()
        fleet = self.fleet
        with fleet._cv:
            for ticket in pending:
                fleet.router.fail_transport(ticket, self.rank)
            fleet._cv.notify_all()

    def close(self) -> None:
        with self._mu:
            self._closing = True
            self.dead = True
        self.client.close()


class Fleet:
    """Spawn, supervise, scale, and route over N replica processes."""

    def __init__(self, replicas: int = 2, capacity: int = 64,
                 max_batch: int = 8, mix: str = "spmv,heat,cipher",
                 warm_requests: int = 6, dispatch_width: int | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 ready_timeout_s: float = 180.0,
                 max_restarts: int = 4,
                 slo=None, autoscaler: Autoscaler | None = None,
                 clock: Clock | None = None,
                 router: Router | None = None, shm: bool = True,
                 jobs_dir: str | None = None):
        self.initial_replicas = replicas
        self.capacity = capacity
        self.max_batch = max_batch
        self.mix = mix
        self.warm_requests = warm_requests
        # pipeline depth: max requests in flight on a replica's channel
        # (PR 15 ran this many blocking sender threads per replica; now
        # it is the router's per-replica capacity on ONE connection)
        self.dispatch_width = dispatch_width or max_batch
        self.shm = shm
        self.ready_timeout_s = ready_timeout_s
        self.max_restarts = max_restarts
        self.slo = slo
        self.autoscaler = autoscaler
        self.clock = clock if clock is not None else Clock()
        self.router = router if router is not None else Router(
            clock=self.clock, capacity=max(capacity * max(replicas, 1), 64))
        self.front = _FleetFrontEnd(self, host, port)
        self._cv = threading.Condition()   # guards the router + fleet maps
        self._procs: dict[int, ReplicaProc] = {}
        self._send_queues: dict[int, queue_mod.Queue] = {}
        self._sender_threads: dict[int, list[threading.Thread]] = {}
        self._restarts = 0
        self._next_rank = 0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.scale_ups = 0
        self.scale_downs = 0
        self.flight_confirmed = 0      # requests confirmed mid-batch in dumps
        # durable long-job lane: a shared job directory every replica
        # mounts (serve/jobs.py).  The front end serves job-* controls
        # against it directly; replicas claim and execute the records.
        self.jobs_dir = (jobs_dir if jobs_dir is not None
                         else os.environ.get(jobs_mod.JOBS_DIR_ENV))
        self.jobs_store = (jobs_mod.JobStore(self.jobs_dir)
                           if self.jobs_dir else None)

    # ------------------------------------------------------------ start

    def start(self) -> "Fleet":
        flight.install_from_env()
        for _ in range(self.initial_replicas):
            self._spawn(incarnation=0)
        deadline = time.monotonic() + self.ready_timeout_s
        while time.monotonic() < deadline:
            with self._cv:
                states = [p.state for p in self._procs.values()]
            if states and all(s == "up" for s in states):
                break
            self._poll_starting()
            time.sleep(0.1)
        else:
            self.close()
            raise TimeoutError(
                f"fleet: replicas not ready in {self.ready_timeout_s}s")
        self._adopt_orphan_jobs()
        self.front.start()
        for name, fn in (("fleet-dispatch", self._dispatch_loop),
                         ("fleet-supervise", self._supervise_loop)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    @property
    def addr(self) -> str:
        return self.front.addr

    # ------------------------------------------------------- spawn/ready

    def _spawn(self, incarnation: int, rank: int | None = None) -> None:
        if rank is None:
            rank = self._next_rank
            self._next_rank += 1
        port = free_port()
        env = dict(os.environ)
        env["JAX_PROCESS_ID"] = str(rank)
        env["CME213_INCARNATION"] = str(incarnation)
        if self.jobs_dir:
            env[jobs_mod.JOBS_DIR_ENV] = self.jobs_dir
        env.setdefault(HEARTBEAT_INTERVAL_ENV, "0.5")
        env.update(propagation_env())
        _template_trace_file(env, rank)
        _template_metrics_file(env, rank)
        cmd = [sys.executable, "-m", "cme213_tpu", "fleet", "worker",
               "--port", str(port),
               "--capacity", str(self.capacity),
               "--max-batch", str(self.max_batch),
               "--mix", self.mix,
               "--warm-requests", str(self.warm_requests)]
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        threading.Thread(target=_pump, args=(rank, proc.stdout, sys.stderr),
                         daemon=True).start()
        rep = ReplicaProc(rank, incarnation, port, proc)
        with self._cv:
            self._procs[rank] = rep
            if rank not in self._send_queues:
                self._send_queues[rank] = queue_mod.Queue()
                t = threading.Thread(
                    target=self._sender_loop, args=(rank,),
                    name=f"fleet-send-r{rank}", daemon=True)
                t.start()
                self._sender_threads[rank] = [t]

    def _poll_starting(self) -> None:
        """Probe starting replicas; register the ones that answer ping."""
        with self._cv:
            starting = [p for p in self._procs.values()
                        if p.state == "starting"]
        for rep in starting:
            if rep.proc.poll() is not None:
                with self._cv:
                    rep.state = "down"
                continue
            try:
                with TransportClient(rep.addr, timeout_s=2.0,
                                     connect_timeout_s=0.5) as c:
                    pong = c.control("ping")
            except (OSError, ConnectionError, ValueError):
                continue
            if not pong.get("ok"):
                continue
            with self._cv:
                rep.state = "up"
                self.router.register_replica(
                    rep.rank, capacity=self.dispatch_width,
                    incarnation=rep.incarnation)
                self._cv.notify_all()
            metrics.counter("fleet.replica_up").inc()

    # -------------------------------------------------------- dispatch

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                a = self.router.next_assignment()
                if a is None:
                    self._cv.wait(0.05)
                    continue
            ticket, rank = a
            self._send_queues[rank].put(ticket)

    def _sender_loop(self, rank: int) -> None:
        """Feed one replica over one pipelined channel.  The loop only
        *sends*; completions (and connection-death requeues) arrive on
        the channel's receiver thread."""
        channel: ReplicaChannel | None = None
        connected_port = None
        q = self._send_queues[rank]
        while not self._stop.is_set():
            try:
                ticket = q.get(timeout=0.2)
            except queue_mod.Empty:
                continue
            if ticket is _SENDER_STOP:
                break
            with self._cv:
                rep = self._procs.get(rank)
                addr = rep.addr if rep is not None else None
                port = rep.port if rep is not None else None
            try:
                if addr is None:
                    raise ConnectionError(f"replica {rank} gone")
                if channel is None or channel.dead or connected_port != port:
                    if channel is not None:
                        channel.close()
                    channel = ReplicaChannel(self, rank, addr,
                                             shm=self.shm)
                    connected_port = port
                channel.send(ticket)
            except (OSError, ConnectionError, ValueError):
                if channel is not None:
                    channel.close()
                channel = None
                with self._cv:
                    self.router.fail_transport(ticket, rank)
                    self._cv.notify_all()
        if channel is not None:
            channel.close()

    def _deliver(self, ticket: Ticket, meta: dict,
                 sections: list = ()) -> None:
        """Answer the client that owns the ticket: v2 clients get the
        sections forwarded as-is on their pipelined connection; v1
        clients get a self-describing JSON doc (sections inlined to
        base64) and their parked connection thread woken."""
        reply = ticket.reply
        if reply is not None:
            conn, wire_rid = reply
            try:
                conn.send_v2(wire.FT_RESPONSE, wire_rid, meta, sections)
            except (ConnectionError, OSError):
                pass                 # client went away; result dropped
            return
        if ticket.done is not None and not ticket.done.is_set():
            ticket.result = (wire.inline_sections(meta, list(sections))
                             if sections else meta)
            ticket.done.set()

    def _observe(self, resp: dict) -> None:
        if self.slo is None:
            return
        status = resp.get("status")
        self.slo.observe(latency_ms=resp.get("latency_ms"),
                         shed=status == SHED, failed=status == FAILED)

    # ------------------------------------------------------ supervision

    def _supervise_loop(self) -> None:
        while not self._stop.is_set():
            self._poll_starting()
            with self._cv:
                reps = list(self._procs.values())
            for rep in reps:
                rc = rep.proc.poll()
                if rc is not None and rep.state in ("up", "starting"):
                    self._handle_death(rep, rc)
                elif rc is not None and rep.state == "retired":
                    pass
            self._autoscale_tick()
            self._stop.wait(0.05)

    def _handle_death(self, rep: ReplicaProc, rc: int) -> None:
        reason = "replica-kill" if rc == -9 or rc == KILL_EXIT else f"exit:{rc}"
        record_event("replica-down", replica=rep.rank,
                     incarnation=rep.incarnation, reason=reason)
        metrics.counter("fleet.replica_down").inc()
        self.flight_confirmed += self._read_flight_dump(rep)
        with self._cv:
            rep.state = "down"
            self.router.mark_down(rep.rank, reason=reason)
            # tickets already handed to this replica's sender queue but
            # not yet sent will fail at the socket and requeue there;
            # nothing is lost either way.
            relaunch = (not self._stop.is_set()
                        and self._restarts < self.max_restarts)
            if relaunch:
                self._restarts += 1
            self._cv.notify_all()
        if relaunch:
            self._spawn(incarnation=rep.incarnation + 1,
                               rank=rep.rank)
        elif self.jobs_store is not None:
            # the dead replica is NOT coming back: move its claimed jobs
            # to a live rank so they resume from their durable epoch
            # rather than sitting orphaned until the next fleet restart.
            with self._cv:
                live = sorted(p.rank for p in self._procs.values()
                              if p.state == "up" and p.rank != rep.rank)
            if live:
                moved = self.jobs_store.reassign_from(
                    str(rep.rank), str(live[0]))
                for jid in moved:
                    record_event("job-reassigned", job=jid,
                                 source=str(rep.rank), target=str(live[0]))
                    metrics.counter("jobs.reassigned").inc()

    def _adopt_orphan_jobs(self) -> None:
        """Fleet restart: job records whose owner rank no longer exists
        (the previous fleet's replicas are all gone) are reassigned to
        the lowest live rank so they resume from their last durable
        epoch."""
        if self.jobs_store is None:
            return
        with self._cv:
            ranks = {str(p.rank) for p in self._procs.values()
                     if p.state == "up"}
        if not ranks:
            return
        target = min(ranks, key=int)
        for rec in self.jobs_store.list_jobs():
            if rec["state"] in jobs_mod.TERMINAL:
                continue
            owner = self.jobs_store.owner(rec["job"])
            if owner is not None and owner not in ranks:
                self.jobs_store.reassign(rec["job"], target)
                record_event("job-reassigned", job=rec["job"],
                             source=owner, target=target)
                metrics.counter("jobs.reassigned").inc()

    def _read_flight_dump(self, rep: ReplicaProc) -> int:
        """Post-mortem: from the dead replica's flight-recorder dump
        (written before the injected SIGKILL), count the requests it had
        accepted but not yet served — the set the ledger requeues.  The
        dump is the *proof*; the in-flight ledger is the mechanism."""
        fdir = os.environ.get(flight.FLIGHT_DIR_ENV)
        if not fdir:
            return 0
        confirmed = 0
        for path in sorted(glob.glob(
                os.path.join(fdir, f"flight-{rep.proc.pid}-*.json"))):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if doc.get("reason") not in ("replica-kill", "rankkill"):
                continue
            counters = (doc.get("metrics") or {}).get("counters", {})
            accepted = counters.get("serve.requests", 0) - sum(
                v for k, v in counters.items()
                if k.startswith("serve.shed."))
            served = sum(1 for e in (doc.get("events") or [])
                         if e.get("event") == "request-served")
            confirmed += max(0, int(accepted) - served)
            print(f"fleet: replica {rep.rank} flight dump {path}: "
                  f"{max(0, int(accepted) - served)} request(s) in flight",
                  file=sys.stderr, flush=True)
        return confirmed

    # ------------------------------------------------------ autoscaling

    def _autoscale_tick(self) -> None:
        if self.autoscaler is None:
            return
        with self._cv:
            if self.slo is not None:
                # burning only transitions inside evaluate(): the fleet
                # is the monitor's driver, there is no Server.step here
                self.slo.evaluate()
            burning = bool(self.slo is not None and self.slo.burning)
            occupancy = self.router.occupancy()
            n = len([p for p in self._procs.values()
                     if p.state in ("up", "starting")])
        decision = self.autoscaler.evaluate(burning, occupancy, n)
        if decision == "up":
            self.scale_ups += 1
            self._spawn(incarnation=0)
        elif decision == "down":
            self.scale_downs += 1
            self._retire_one()

    def _retire_one(self) -> None:
        with self._cv:
            up = [p for p in self._procs.values() if p.state == "up"]
            if len(up) <= 1:
                return
            rep = max(up, key=lambda p: p.rank)
            rep.state = "retired"
            self.router.mark_retiring(rep.rank)
        # drain: wait (bounded) for its in-flight work, then stop it
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with self._cv:
                st = self.router.replicas.get(rep.rank)
                if st is None or st.inflight == 0:
                    break
            time.sleep(0.05)
        with self._cv:
            st = self.router.replicas.get(rep.rank)
            if st is not None:
                st.up = False
        record_event("replica-down", replica=rep.rank,
                     incarnation=rep.incarnation, reason="retired")
        metrics.counter("fleet.replica_down").inc()
        rep.proc.terminate()

    # ----------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._cv:
            routing = self.router.state()
            states = {f"r{p.rank}": p.state for p in self._procs.values()}
        routing["replica_states"] = states
        routing["replicas_up"] = sum(1 for s in states.values() if s == "up")
        routing["scale_ups"] = self.scale_ups
        routing["scale_downs"] = self.scale_downs
        routing["flight_confirmed"] = self.flight_confirmed
        return routing

    # ----------------------------------------------------------- close

    def close(self) -> None:
        self._stop.set()
        self.front.close()
        with self._cv:
            self._cv.notify_all()
            reps = list(self._procs.values())
            queues = list(self._send_queues.values())
        for q in queues:
            q.put(_SENDER_STOP)
        for rep in reps:
            if rep.proc.poll() is None:
                rep.proc.terminate()
        for rep in reps:
            try:
                rep.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                rep.proc.kill()


class _FleetFrontEnd(FrameServer):
    """The fleet's client-facing socket.  v2 connections pipeline:
    each accepted frame becomes a ticket carrying its binary sections
    and its reply handle, and the reader moves straight to the next
    frame — responses flow back whenever a replica answers.  v1
    connections keep the legacy contract: the connection thread parks
    on its ticket until the response arrives (possibly after a
    requeue)."""

    def __init__(self, fleet: Fleet, host: str, port: int):
        super().__init__(host, port)
        self.fleet = fleet

    def handle_v2(self, conn, rid: int, meta: dict, sections: list,
                  read_s: float = 0.0) -> None:
        fleet = self.fleet
        with fleet._cv:
            ticket = fleet.router.submit(meta)
            if ticket is not None:
                ticket.sections = sections    # pass through, no re-encode
                ticket.reply = (conn, rid)
                fleet._cv.notify_all()
        if ticket is None:
            conn.send_v2(wire.FT_RESPONSE, rid,
                         {"rid": -1, "op": meta.get("op"), "status": SHED,
                          "reason": QUEUE_FULL,
                          "tenant": meta.get("tenant", "default")})

    def handle(self, doc: dict) -> dict:
        with self.fleet._cv:
            ticket = self.fleet.router.submit(doc)
            if ticket is not None:
                ticket.done = threading.Event()
                self.fleet._cv.notify_all()
        if ticket is None:
            return {"rid": -1, "op": doc.get("op"), "status": SHED,
                    "reason": QUEUE_FULL,
                    "tenant": doc.get("tenant", "default")}
        if not ticket.done.wait(RESPONSE_TIMEOUT_S):
            return {"rid": ticket.seq, "op": ticket.op, "status": FAILED,
                    "reason": "transport-timeout", "tenant": ticket.tenant}
        return ticket.result

    def control(self, doc: dict) -> dict:
        kind = doc.get("control")
        if isinstance(kind, str) and kind.startswith("job-"):
            store = self.fleet.jobs_store
            if store is None:
                return {"ok": False,
                        "error": "fleet has no --jobs-dir; job lane is off"}
            return jobs_mod.handle_control(store, doc)
        return super().control(doc)

    def stats(self) -> dict:
        out = self.fleet.stats()
        if self.fleet.jobs_store is not None:
            states: dict[str, int] = {}
            for rec in self.fleet.jobs_store.list_jobs():
                states[rec["state"]] = states.get(rec["state"], 0) + 1
            out["jobs"] = states
        return out


# ------------------------------------------------------------ worker

def worker_main(argv: list[str]) -> int:
    """Entry point of one replica process (``fleet worker``): build a
    server, warm its program cache, bind the socket transport in
    background-batcher drive, heartbeat until terminated."""
    import argparse

    ap = argparse.ArgumentParser(prog="fleet worker")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--capacity", type=int, default=64)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--mix", default="spmv,heat,cipher")
    ap.add_argument("--warm-requests", type=int, default=6)
    ap.add_argument("--max-seconds", type=float, default=600.0)
    args = ap.parse_args(argv)

    from ..core.faults import incarnation
    from ..dist.supervisor import heartbeat_from_env
    from .server import Server
    from .warmup import warm_buckets

    flight.install_from_env()
    rank = os.environ.get("JAX_PROCESS_ID", "0")
    if args.warm_requests > 0:
        warmed = warm_buckets(args.mix, requests=args.warm_requests,
                              max_batch=args.max_batch)
        print(f"fleet worker r{rank}: warmed {len(warmed)} buckets",
              flush=True)
    server = Server(capacity=args.capacity, max_batch=args.max_batch)
    ts = TransportServer(server, port=args.port, drive="thread",
                         kill_guard=True)
    jobs_dir = os.environ.get(jobs_mod.JOBS_DIR_ENV)
    if jobs_dir:
        store = jobs_mod.JobStore(jobs_dir)
        ts.attach_jobs(jobs_mod.JobExecutor(store, server=server, rank=rank))
        print(f"fleet worker r{rank}: job lane on {jobs_dir}", flush=True)
    ts.start()
    record_event("replica-up", replica=int(rank),
                 incarnation=incarnation(), addr=ts.addr)
    metrics.counter("fleet.replica_up").inc()
    print(f"fleet worker r{rank}: serving on {ts.addr} "
          f"(incarnation {incarnation()})", flush=True)
    # the supervisor retires/tears down replicas with SIGTERM
    # (``proc.terminate()``); route it into KeyboardInterrupt so the
    # transport closes and buffered trace spans reach the sink instead
    # of dying with the process
    import signal

    def _graceful(signum, frame):
        raise KeyboardInterrupt

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
    except ValueError:
        pass
    hb = heartbeat_from_env()
    deadline = time.monotonic() + args.max_seconds
    try:
        while time.monotonic() < deadline:
            if hb is not None:
                hb.beat(ts.batches)
            time.sleep(0.25)
    except KeyboardInterrupt:
        pass
    finally:
        ts.close()
    return 0
