"""Shared-memory frame lane for same-host transport clients.

When client and server share a host — the fleet's own front-end →
replica dispatch is the canonical case — pushing megabyte payloads
through the loopback socket costs two kernel copies and a wakeup per
frame.  This lane moves the *bytes* through a ``multiprocessing.
shared_memory`` ring instead and keeps the socket for what it is good
at: ordering and readiness.  Each v2 frame that fits a slot is packed
into shared memory and announced by a tiny ``FT_SHM`` doorbell frame
over the existing connection; frames that don't fit (or when no slot
credit is free) fall back to plain socket frames transparently —
correctness never depends on the lane.

**Negotiation** (one control round-trip, client-initiated): the client
creates two segments — ``c2s`` (client writes) and ``s2c`` (server
writes) — and sends ``{"control": "shm-setup", "c2s": name, "s2c":
name, "slots": N, "slot_bytes": B}``.  A server that can attach both
replies ``{"ok": true}`` and the lane is live in both directions; any
failure leaves the connection on pure sockets.  The client owns the
segments' lifetime (creates and unlinks); the server only attaches.

**Credit scheme**: the writer holds one credit per slot.  A send takes
a credit, copies the packed frame in, and doorbells ``{"slot": i,
"len": n}``.  The receiver parses the frame *out* of the slot (arrays
are copied on parse — ``wire.parse_frame``) and returns the credit
with ``{"control": "shm-ack", "slot": i}`` riding the same socket.
Doorbells and ordinary frames share one ordered byte stream, so
mixed-lane traffic on a connection stays in submission order.
"""

from __future__ import annotations

import threading

from . import wire

#: lane defaults: 8 slots x 1 MiB covers the serving mix's payloads
#: (spmv 1k-float problems ~ tens of KiB) with room for pipelining
DEFAULT_SLOTS = 8
DEFAULT_SLOT_BYTES = 1 << 20


def _shared_memory():
    # imported lazily so platforms without it degrade to sockets
    from multiprocessing import shared_memory
    return shared_memory


def _unregister(name: str) -> None:
    """Detach a segment from this process's resource tracker: only the
    creating side owns cleanup, attachers must not unlink at exit."""
    try:    # pragma: no cover - tracker internals vary by version
        from multiprocessing import resource_tracker
        resource_tracker.unregister(f"/{name}", "shared_memory")
    except Exception:
        pass


def _register(name: str) -> None:
    """Re-register before an explicit unlink — ``unlink()`` always
    unregisters, and an attach in the *same* process (tests) would have
    already removed the tracker entry via :func:`_unregister`."""
    try:    # pragma: no cover - tracker internals vary by version
        from multiprocessing import resource_tracker
        resource_tracker.register(f"/{name}", "shared_memory")
    except Exception:
        pass


class ShmRing:
    """One direction of the lane: a slotted shared-memory segment.
    Purely memory — credits live with the writer (:class:`ShmTx`)."""

    def __init__(self, name: str | None = None,
                 slots: int = DEFAULT_SLOTS,
                 slot_bytes: int = DEFAULT_SLOT_BYTES,
                 create: bool = False):
        sm = _shared_memory()
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        self.created = create
        if create:
            self.shm = sm.SharedMemory(create=True,
                                       size=self.slots * self.slot_bytes)
        else:
            self.shm = sm.SharedMemory(name=name)
            _unregister(self.shm.name)
        self.name = self.shm.name

    def slot_view(self, slot: int, length: int | None = None) -> memoryview:
        off = slot * self.slot_bytes
        end = off + (self.slot_bytes if length is None else length)
        return self.shm.buf[off:end]

    def close(self) -> None:
        try:
            self.shm.close()
        except (OSError, BufferError):
            pass
        if self.created:
            try:
                _register(self.name)
                self.shm.unlink()
            except (OSError, FileNotFoundError):
                pass


class ShmTx:
    """Writer half: slot credits + frame copy-in.  ``try_send`` returns
    doorbell meta on success or None (no credit / frame too big), in
    which case the caller sends the frame over the socket instead."""

    def __init__(self, ring: ShmRing):
        self.ring = ring
        self._mu = threading.Lock()
        self._free = list(range(ring.slots))
        self.sent = 0          # frames through the lane
        self.fallbacks = 0     # frames that went to the socket instead

    def try_send(self, bufs: list) -> dict | None:
        total = wire.frame_nbytes(bufs)
        if total > self.ring.slot_bytes:
            with self._mu:
                self.fallbacks += 1
            return None
        with self._mu:
            if not self._free:
                self.fallbacks += 1
                return None
            slot = self._free.pop()
        view = self.ring.slot_view(slot)
        o = 0
        for b in bufs:
            mv = b if isinstance(b, memoryview) else memoryview(b)
            n = len(mv)
            view[o:o + n] = mv
            o += n
        with self._mu:
            self.sent += 1
        return {"slot": slot, "len": total}

    def ack(self, slot: int) -> None:
        with self._mu:
            if slot not in self._free:
                self._free.append(slot)

    def stats(self) -> dict:
        with self._mu:
            return {"sent": self.sent, "fallbacks": self.fallbacks,
                    "free": len(self._free), "slots": self.ring.slots}


class ShmLane:
    """Both directions of a negotiated lane, from either endpoint's
    point of view: ``tx`` is the ring this side writes (plus credits),
    ``rx`` the ring it parses doorbelled frames out of."""

    def __init__(self, tx_ring: ShmRing, rx_ring: ShmRing):
        self.tx = ShmTx(tx_ring)
        self.rx = rx_ring

    def read(self, slot: int, length: int):
        """Parse the frame a doorbell announced; the slot is free for
        the writer again the moment this returns (arrays were copied)."""
        view = self.rx.slot_view(slot, length)
        try:
            return wire.parse_frame(view)
        finally:
            view.release()

    def close(self) -> None:
        self.tx.ring.close()
        self.rx.close()


def create_client_lane(slots: int = DEFAULT_SLOTS,
                       slot_bytes: int = DEFAULT_SLOT_BYTES) -> ShmLane:
    """Client side: create both segments (the client owns unlink)."""
    c2s = ShmRing(slots=slots, slot_bytes=slot_bytes, create=True)
    try:
        s2c = ShmRing(slots=slots, slot_bytes=slot_bytes, create=True)
    except Exception:
        c2s.close()
        raise
    return ShmLane(tx_ring=c2s, rx_ring=s2c)


def attach_server_lane(setup: dict) -> ShmLane:
    """Server side: attach to the client's segments from an
    ``shm-setup`` control document.  Raises on any failure — the caller
    replies not-ok and the connection stays on sockets."""
    slots = int(setup["slots"])
    slot_bytes = int(setup["slot_bytes"])
    rx = ShmRing(name=setup["c2s"], slots=slots, slot_bytes=slot_bytes)
    try:
        tx = ShmRing(name=setup["s2c"], slots=slots,
                     slot_bytes=slot_bytes)
    except Exception:
        rx.close()
        raise
    return ShmLane(tx_ring=tx, rx_ring=rx)


def setup_doc(lane: ShmLane) -> dict:
    """The client's ``shm-setup`` control fields for ``lane``."""
    return {"c2s": lane.tx.ring.name, "s2c": lane.rx.name,
            "slots": lane.tx.ring.slots,
            "slot_bytes": lane.tx.ring.slot_bytes}
