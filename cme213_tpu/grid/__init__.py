from .grid import HaloGrid, make_initial_grid, interior, save_grid_to_file

__all__ = ["HaloGrid", "make_initial_grid", "interior", "save_grid_to_file"]
