"""Evidence summarizer (bench/report.py) — the data.ods curation layer."""

import json

from cme213_tpu.bench.report import generate, main


def _fixture(tmp_path):
    d = tmp_path / "results"
    (d / "cpu").mkdir(parents=True)
    (d / "jobs").mkdir()
    (d / "heat_bandwidth.csv").write_text(
        "size,order,gbs\n4000,8,123.4\n")
    (d / "cpu" / "sort_threads.csv").write_text(
        "threads,merge_s\n1,1.9\n2,2.0\n")
    (d / "jobs" / "camp.jobs.csv").write_text(
        "point,rc\n0,0\n")
    (d / "bench_f32.json").write_text(json.dumps({
        "metric": "heat2d", "value": 123.4, "unit": "GB/s",
        "vs_baseline": 5.15, "pct_hbm_peak": 15.1,
        "kernels": [{"kernel": "xla", "ok": True, "gbs": 14.6}],
    }) + "\n")
    (d / "smoke_tpu.txt").write_text("ALL PALLAS KERNELS OK\n")
    return d


def test_generate_covers_all_artifacts(tmp_path):
    doc = generate(str(_fixture(tmp_path)))
    assert "## Headline bench (f32)" in doc
    assert "5.15× the GTX-580 baseline, 15.1% of HBM peak" in doc
    assert "| kernel | ok | gbs |" in doc
    assert "### heat_bandwidth.csv" in doc
    assert "| 4000 | 8 | 123.4 |" in doc
    assert "### sort_threads.csv" in doc
    assert "### camp.jobs.csv" in doc
    assert "ALL PALLAS KERNELS OK" in doc


def test_missing_artifacts_are_skipped(tmp_path):
    d = tmp_path / "empty"
    d.mkdir()
    doc = generate(str(d))
    assert "Headline bench" not in doc
    assert "Device sweeps" not in doc


def test_main_writes_file(tmp_path):
    d = _fixture(tmp_path)
    out = tmp_path / "DATA.md"
    assert main(["--dir", str(d), "--out", str(out)]) == 0
    assert out.read_text().startswith("# Measurement data")
