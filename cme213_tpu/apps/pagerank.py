"""PageRank workload driver — CSR gather propagation.

TPU-native driver for the reference hw1 PageRank workload
(``hw/hw1/programming/pagerank.cu:146-249``): builds the same synthetic CSR
graph (cyclic out-degrees ``i % (2·avg−1) + 1``, uniformly random neighbors,
``pagerank.cu:185-204``), runs the edge-parallel propagate for an even number
of iterations, and verifies against the host golden with ULP-10.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..core import PhaseTimer
from ..ops.gather import csr_row_ids, pagerank_iterate
from ..verify import check_ulp, golden


@dataclass
class Graph:
    indices: np.ndarray   # (n+1,) uint32 CSR row offsets
    edges: np.ndarray     # (E,) uint32 neighbor ids
    inv_deg: np.ndarray   # (n,) float32 1/out-degree
    rank0: np.ndarray     # (n,) float32 uniform 1/n
    num_nodes: int
    avg_edges: int


def build_graph(num_nodes: int, avg_edges: int, seed: int = 0) -> Graph:
    """Synthetic graph with the reference's degree pattern
    (pagerank.cu:185-204)."""
    rng = np.random.default_rng(seed)
    degs = (np.arange(num_nodes) % (2 * avg_edges - 1) + 1).astype(np.uint32)
    indices = np.zeros(num_nodes + 1, dtype=np.uint32)
    np.cumsum(degs, out=indices[1:])
    total = int(indices[-1])
    if total >= num_nodes * avg_edges + avg_edges:
        raise ValueError("more edges than we have space for")
    edges = rng.integers(0, num_nodes, size=total, dtype=np.uint32)
    inv_deg = (1.0 / degs.astype(np.float32)).astype(np.float32)
    rank0 = np.full(num_nodes, np.float32(1.0) / np.float32(num_nodes), np.float32)
    return Graph(indices, edges, inv_deg, rank0, num_nodes, avg_edges)


def run_pagerank(graph: Graph, nr_iterations: int, timer: PhaseTimer | None = None):
    """Device PageRank: returns the final rank vector (jnp array)."""
    assert nr_iterations % 2 == 0  # pagerank.cu:61,127
    indices = jnp.asarray(graph.indices)
    edges = jnp.asarray(graph.edges.astype(np.int32))
    row_ids = csr_row_ids(indices, graph.edges.shape[0])
    inv_deg = jnp.asarray(graph.inv_deg)
    rank0 = jnp.asarray(graph.rank0)
    timer = timer or PhaseTimer()
    with timer.phase("gpu graph propagate") as ph:
        out = pagerank_iterate(row_ids, edges, rank0, inv_deg,
                               graph.num_nodes, nr_iterations)
        ph.block(out)
    return out


def pagerank_step(graph: Graph):
    """``(state0, step_fn)`` for the checkpointed/long-job lane:
    ``step_fn(rank, k)`` advances the rank vector by ``k`` propagate
    sweeps.  Even ``k`` rides :func:`~..ops.gather.pagerank_iterate`
    (the reference's fused even-iteration loop, pagerank.cu:61,127);
    odd ``k`` — possible only after a RESOURCE chunk-halving — falls
    back to per-sweep :func:`~..ops.gather.pagerank_propagate` calls,
    the same program one iteration at a time."""
    from ..ops.gather import pagerank_propagate

    indices = jnp.asarray(graph.indices)
    edges = jnp.asarray(graph.edges.astype(np.int32))
    row_ids = csr_row_ids(indices, graph.edges.shape[0])
    inv_deg = jnp.asarray(graph.inv_deg)

    def step_fn(state, k):
        rank = jnp.asarray(state)
        k = int(k)
        if k >= 2 and k % 2 == 0:
            return pagerank_iterate(row_ids, edges, rank, inv_deg,
                                    graph.num_nodes, k)
        for _ in range(k):
            rank = pagerank_propagate(row_ids, edges, rank, inv_deg,
                                      graph.num_nodes)
        return rank

    return graph.rank0, step_fn


def run_pagerank_checkpointed(graph: Graph, nr_iterations: int, path: str,
                              every: int = 0, tracker=None,
                              stall_epochs: int = 25) -> np.ndarray:
    """Checkpointed PageRank: the power iteration in epoch-sized chunks
    through ``core.checkpoint.run_with_checkpoints``, resuming from
    ``path`` when a checkpoint exists.  Each accepted chunk feeds a
    ``ConvergenceTracker`` (one ``solver-progress`` event per epoch:
    residual, delta-norm, iters/s — the convergence trace the
    interactive driver above never emitted), with ``stall_epochs``
    registered on the tracker so a flatlined solve is called STALLED
    instead of burning its whole budget.  Chunking is arithmetic-neutral
    (every iteration runs the same propagate program), so the final
    ranks are bitwise-equal to an uninterrupted :func:`run_pagerank` of
    the same even iteration count."""
    from ..core.checkpoint import run_with_checkpoints
    from ..core.numerics import ConvergenceTracker

    if tracker is None:
        tracker = ConvergenceTracker("pagerank", stall_epochs=stall_epochs)
    state0, step_fn = pagerank_step(graph)
    out = run_with_checkpoints(step_fn, state0, nr_iterations, path,
                               every=every, op="pagerank", tracker=tracker)
    return np.asarray(out)


def bytes_moved(graph: Graph, nr_iterations: int) -> int:
    """Exact byte accounting for bandwidth reports — delegates to the
    centralized cost model (``core/roofline.pagerank_cost``), as
    instrumented in the reference sweep harness
    (``hw/hw1/programming/analysis/pagerank.cu:47-62``): per iteration,
    each edge reads a 4B neighbor id + 4B rank + 4B inv_deg, each node
    reads 2×4B offsets and writes a 4B rank."""
    from ..core.roofline import pagerank_cost

    return pagerank_cost(graph.num_nodes, graph.edges.shape[0],
                         nr_iterations).nbytes


def main(num_nodes: int = 1 << 21, avg_edges: int = 8, iterations: int = 20,
         seed: int = 0) -> bool:
    """Full driver: build → device iterate → host golden → ULP check
    (the reference main, pagerank.cu:146-249)."""
    timer = PhaseTimer(verbose=True)
    graph = build_graph(num_nodes, avg_edges, seed)
    out = np.asarray(run_pagerank(graph, iterations, timer))
    with timer.phase("host graph propagate"):
        ref = golden.host_graph_iterate(
            graph.indices, graph.edges, graph.rank0, graph.inv_deg, iterations
        )
    res = check_ulp(ref, out, max_ulps=10, label="pagerank")
    print("Worked! TPU and reference output match." if res
          else f"Output of TPU version and normal version didn't match! {res.message}")
    return bool(res)


if __name__ == "__main__":
    main()
