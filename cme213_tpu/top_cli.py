"""``python -m cme213_tpu top`` — a live fleet console over the collector.

The reference watches an MPI job with ``qstat`` plus per-rank timing
tables printed at the end (hw5); this is the interactive equivalent for a
gang or serving fleet: per-rank rows (state, step, heartbeat age, last
span, breaker/degraded flags), fleet gauges (restarts, commits + lag,
sheds, SLO burns, requests), the hottest spans, the slowest request
hops (each line names the rid and trace id ``trace waterfall`` takes),
and a recent-events ribbon — refreshed in place from the per-rank
trace sinks that ``core/collector.py`` tails.

Deterministic modes for tests and CI:

- ``--once``: render one frame from whatever the sinks hold and exit.
- ``--json``: emit the collector's merged state as sorted-key JSON
  (ages are relative to the newest observed event, not the wall clock,
  so re-rendering an idle capture is byte-stable).

``--hb-dir`` folds the supervisor's file heartbeats
(``dist/supervisor.py``) into the view — useful when a rank's sink is
unconfigured but its heartbeat file is landing.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .core.collector import Collector

_CLEAR = "\x1b[2J\x1b[H"


def _fmt(value, width: int) -> str:
    s = "-" if value is None else str(value)
    return s[:width].ljust(width)


def _flags(row: dict) -> str:
    flags = []
    if row.get("breakers_open"):
        flags.append(f"brk:{row['breakers_open']}")
    if row.get("degraded"):
        flags.append("degraded")
    return ",".join(flags) or "-"


def render_top(state: dict, out=None) -> None:
    """One console frame from :meth:`Collector.state` output."""
    out = out or sys.stdout
    ids = state["trace_ids"]
    trace = ids[0] if len(ids) == 1 else f"{len(ids)} ids"
    out.write(f"cme213 fleet · {len(state['ranks'])} proc(s) · "
              f"{state['events']} event(s) · trace {trace or '-'}\n")

    out.write(f"{'PROC':<7}{'ROLE':<9}{'STATE':<9}{'PID':<8}{'INC':<4}"
              f"{'STEP':<7}{'HB AGE':<8}{'OCC':<6}{'LAST SPAN':<22}"
              f"{'FLAGS'}\n")
    for key, row in state["ranks"].items():
        hb = row.get("heartbeat_age_s")
        occ = row.get("occupancy")
        out.write(_fmt(key, 7) + _fmt(row.get("role"), 9)
                  + _fmt(row.get("state"), 9)
                  + _fmt(row.get("pid"), 8)
                  + _fmt(row.get("incarnation"), 4)
                  + _fmt(row.get("step"), 7)
                  + _fmt(f"{hb:.1f}s" if hb is not None else None, 8)
                  + _fmt(f"{occ:.2f}" if occ is not None else None, 6)
                  + _fmt(row.get("last_span"), 22)
                  + _flags(row) + "\n")

    fl = state["fleet"]
    lag = state.get("commit_lag_s")
    commit = state.get("last_commit") or {}
    out.write("fleet: "
              f"launches={fl.get('launches', 0)} "
              f"restarts={fl.get('restarts', 0)} "
              f"verdicts={fl.get('verdicts', 0)} "
              f"commits={fl.get('commits', 0)}"
              + (f"@epoch{commit.get('epoch')}" if commit else "")
              + (f" lag={lag}s" if lag is not None else "")
              + f" sheds={fl.get('sheds', 0)}"
              f" slo_burns={fl.get('slo_burns', 0)}"
              f" breaker_opens={fl.get('breaker_opens', 0)}"
              f" requests={fl.get('requests', 0)}\n")
    if any(fl.get(k) for k in ("replica_ups", "replica_downs", "routed",
                               "requeues", "scale_ups", "scale_downs")):
        out.write("serving: "
                  f"replicas_up={fl.get('replica_ups', 0)} "
                  f"replicas_down={fl.get('replica_downs', 0)} "
                  f"routed={fl.get('routed', 0)} "
                  f"requeues={fl.get('requeues', 0)} "
                  f"scale=+{fl.get('scale_ups', 0)}"
                  f"/-{fl.get('scale_downs', 0)}\n")
    out.write("numerics: "
              f"drift={fl.get('drift_samples', 0)}"
              f"/{fl.get('drift_over_budget', 0)}over "
              f"demotions={fl.get('drift_demotions', 0)} "
              f"sentinels={fl.get('sentinel_trips', 0)} "
              f"conformance_failures={fl.get('conformance_failures', 0)} "
              f"attribution_mismatches="
              f"{fl.get('attribution_mismatches', 0)}\n")

    solvers = state.get("solvers") or {}
    if solvers:
        out.write("solvers:\n")
        for op, row in solvers.items():
            verdict = "STALLED" if row.get("stalled") else "converging"
            res = row.get("residual")
            ips = row.get("iters_per_s")
            out.write(f"  {op:<14} step={row.get('step')} "
                      f"residual={res if res is not None else '-'} "
                      f"iters/s={ips if ips is not None else '-'} "
                      f"{verdict}\n")

    jobs = state.get("jobs") or {}
    if jobs:
        out.write("jobs:\n")
        for jid, row in jobs.items():
            epoch = row.get("epoch")
            total = row.get("total_epochs")
            res = row.get("residual")
            out.write(f"  {jid:<18} {row.get('op') or '?':<10} "
                      f"{row.get('state') or '?':<10} "
                      f"epoch={epoch if epoch is not None else '-'}"
                      f"/{total if total is not None else '-'} "
                      f"residual={res if res is not None else '-'} "
                      f"resumes={row.get('resumes', 0)} "
                      f"preempt={row.get('preemptions', 0)}\n")

    spans = sorted(state["spans"].items(),
                   key=lambda kv: kv[1]["total_ms"], reverse=True)[:5]
    if spans:
        out.write("spans (top by total ms):\n")
        for name, agg in spans:
            out.write(f"  {name:<28} n={agg['count']:<6} "
                      f"total={agg['total_ms']}ms max={agg['max_ms']}ms\n")

    slowest = state.get("slowest_traces") or []
    if slowest:
        out.write("slowest requests (waterfall rid · trace):\n")
        for e in slowest[:5]:
            tail = []
            if e.get("requeues"):
                tail.append(f"{e['requeues']} requeue(s)")
            if e.get("status") not in (None, "ok"):
                tail.append(str(e["status"]))
            out.write(f"  {e['ms']:>9.1f}ms {e['span']:<18} "
                      f"rid={e['rid']} trace={e['trace']}"
                      + (f" [{', '.join(tail)}]" if tail else "") + "\n")

    recent = state["recent"][-8:]
    if recent:
        out.write("recent: "
                  + " · ".join(f"{e['rank']}:{e['event']}" for e in recent)
                  + "\n")
    if state["malformed"]:
        out.write(f"({state['malformed']} malformed line(s) skipped)\n")


def _fold_heartbeats(state: dict, hb_dir: str) -> None:
    from .dist.supervisor import read_all_heartbeats

    beats = read_all_heartbeats(hb_dir)
    state["heartbeats"] = {str(r): b for r, b in sorted(beats.items())}
    for rank, beat in beats.items():
        row = state["ranks"].get(f"r{rank}")
        if row is not None and row.get("step") is None:
            row["step"] = beat.get("step")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="cme213_tpu top",
        description="live fleet console over per-rank trace sinks")
    ap.add_argument("files", nargs="+",
                    help="sink files or globs (re-expanded every poll)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged state as sorted-key JSON "
                         "(implies one frame per refresh)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="seconds between refreshes in live mode")
    ap.add_argument("--iterations", type=int, default=None,
                    help="stop after this many refreshes (live mode)")
    ap.add_argument("--hb-dir", default=None,
                    help="also fold supervisor heartbeat files from this "
                         "directory into the view")
    args = ap.parse_args(argv)

    coll = Collector(args.files)

    def frame(clear: bool) -> None:
        coll.poll()
        state = coll.state()
        if args.hb_dir:
            _fold_heartbeats(state, args.hb_dir)
        if args.json:
            print(json.dumps(state, sort_keys=True, default=str),
                  flush=True)
        else:
            if clear:
                sys.stdout.write(_CLEAR)
            render_top(state, sys.stdout)
            sys.stdout.flush()

    if args.once:
        frame(clear=False)
        return 0
    done = 0
    try:
        while args.iterations is None or done < args.iterations:
            frame(clear=not args.json)
            done += 1
            if args.iterations is not None and done >= args.iterations:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
