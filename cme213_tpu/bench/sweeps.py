"""Benchmark sweep drivers → CSV (reference L7 analysis harness).

Re-creates the reference's dedicated sweep programs and spreadsheets:

- ``cipher_vector_length_sweep`` — device bandwidth vs array length for the
  three cipher variants (``hw/hw1/programming/analysis/cipher_vl.cu:154-159``,
  CSV ``data_bandwidth_vector_length.csv``).
- ``pagerank_avg_edges_sweep``   — bandwidth vs average out-degree 2..20 with
  exact byte accounting (``analysis/pagerank.cu:47-62,172-174``, CSV
  ``bandwidth_vs_avg_edges.csv`` with columns avg_edges, ms, bytes, GB/s).
- ``heat_sweep``                 — GB/s and GFLOP/s over grid sizes × orders
  × {xla, pallas} kernels (the ``data/data.ods`` tables).
- ``sort_thread_sweep``          — elements/s vs thread count for the native
  sorts (the PBS harness ``pa4.pbs:20-28`` + ``data.ods``).
- ``spmv_suite_sweep``           — runtime over the Bell/Garland-shaped suite
  (``do_test.sh`` + final-report tables).

Each returns a list of row dicts and can write them as CSV via ``write_csv``.

Byte/flop accounting is centralized in ``core/roofline.py`` (one cost
model per op family, dtype-aware by construction), and every row carries
``pct_peak`` + ``bound`` columns — achieved bandwidth as a fraction of
the detected device's peak and the memory-vs-compute roofline verdict —
so a 14 GB/s cell reads as "~2% of HBM peak, memory-bound", the way the
reference's grading tables quote every kernel.  Coverage tables (rows
without a timing) carry the columns empty.
"""

from __future__ import annotations

import csv
import time

import numpy as np


def _attrib(gbs: float, gflops: float = 0.0) -> dict:
    """``pct_peak``/``bound`` columns for a measured row (empty strings
    when there is no signal or the device has no peak entry)."""
    from ..core import roofline

    if not gbs or gbs <= 0:
        return {"pct_peak": "", "bound": ""}
    att = roofline.attribute(gbs, gflops)
    if att["pct_peak"] is None:
        return {"pct_peak": "", "bound": ""}
    return {"pct_peak": att["pct_peak"], "bound": att["bound"]}


def write_csv(rows: list[dict], path: str) -> None:
    if not rows:
        return
    with open(path, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        w.writeheader()
        w.writerows(rows)


def _raise_if_device_error(e: Exception) -> None:
    """Re-raise device/tunnel failures instead of recording them as data.

    A dead tunnel mid-sweep would otherwise fill the remaining cells with
    error rows and write a 'complete' CSV that the capture layer never
    retries — only *sticky* per-cell failures (compile/lowering bugs)
    belong in the table; device failures must fail the sweep so
    ``tpu_capture.sh``'s DEVICE_ERR classifier re-runs it next window.
    """
    msg = str(e)
    if any(tag in msg for tag in
           ("UNAVAILABLE", "DEADLINE", "unreachable", "device error")):
        raise e


def _time_ms(fn, *args, iters: int = 5) -> float:
    import jax

    jax.block_until_ready(fn(*args))  # compile
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def _time_donated_ms(runner, u0) -> float:
    """Warmup + timed run of a buffer-donating heat loop.

    Each call gets a fresh device copy of ``u0`` (the loops donate their
    input), and the H2D upload is *blocked on before the clock starts* —
    ``jnp.array``/``device_put`` are async, so timing ``runner(jnp.array(
    u0))`` would otherwise hide the multi-second tunnel upload inside the
    timed region and deflate every bandwidth column.
    """
    import jax
    import jax.numpy as jnp

    jax.block_until_ready(runner(jax.block_until_ready(jnp.array(u0))))
    u = jax.block_until_ready(jnp.array(u0))
    t0 = time.perf_counter()
    jax.block_until_ready(runner(u))
    return (time.perf_counter() - t0) * 1e3


def cipher_vector_length_sweep(steps: int = 10, max_bytes: int = 1 << 24,
                               shift: int = 17) -> list[dict]:
    import jax.numpy as jnp

    from ..apps.corpus import load_corpus
    from ..core.roofline import cipher_cost
    from ..ops import shift_cipher, shift_cipher_packed

    # real-text input, tiled to length — the reference sweeps buffers
    # carved from its novel input, not random bytes (loaded once: per-step
    # reloads would re-read, or worse regenerate, the 1.25 MB corpus)
    base = load_corpus()
    rows = []
    for i in range(1, steps + 1):
        n = max(64, (max_bytes * i // steps) // 64 * 64)
        data = jnp.asarray(np.tile(base, -(-n // base.size))[:n])
        cost = cipher_cost(n)
        row = {"length": n}
        for name, fn in [
            ("char_gbs", lambda d: shift_cipher(d, shift)),
            ("uint_gbs", lambda d: shift_cipher_packed(d, shift, 4)),
            ("uint2_gbs", lambda d: shift_cipher_packed(d, shift, 8)),
        ]:
            ms = _time_ms(fn, data)
            row[name] = round(cost.gbs(ms), 3)
        # the fastest variant is the device-capability signal the
        # reference's bandwidth plot reads off this table
        row.update(_attrib(max(row["char_gbs"], row["uint_gbs"],
                               row["uint2_gbs"])))
        rows.append(row)
    return rows


def pagerank_avg_edges_sweep(num_nodes: int = 1 << 18,
                             edges_range=range(2, 21),
                             iterations: int = 20) -> list[dict]:
    from ..apps.pagerank import build_graph, run_pagerank
    from ..core.roofline import pagerank_cost

    rows = []
    for avg in edges_range:
        g = build_graph(num_nodes, avg, seed=avg)
        # warm up with the SAME iteration count: it is a static jit arg, so
        # any other count would leave compilation inside the timed bracket
        np.asarray(run_pagerank(g, iterations))
        t0 = time.perf_counter()
        out = run_pagerank(g, iterations)
        np.asarray(out)
        ms = (time.perf_counter() - t0) * 1e3
        cost = pagerank_cost(g.num_nodes, g.edges.shape[0], iterations)
        rows.append({
            "avg_edges": avg,
            "ms": round(ms, 3),
            "bytes": cost.nbytes,
            "gbs": round(cost.gbs(ms), 3),
            **_attrib(cost.gbs(ms), cost.gflops(ms)),
        })
    return rows


def heat_sweep(sizes=(1000, 2000, 4000), orders=(2, 4, 8),
               iters: int = 100, dtype: str = "f32",
               ks=(1, 8)) -> list[dict]:
    """Grid sizes × orders × kernels × dtype — the ``data/data.ods`` table.

    ``dtype='f64'`` reproduces the reference's double-precision rows
    (``jax_enable_x64`` must be on); the Pallas pipeline kernels are
    f32-only on TPU, so f64 rows measure the XLA kernel alone.
    """
    import jax
    import jax.numpy as jnp

    from ..config import SimParams
    from ..core.roofline import heat_cost
    from ..grid import make_initial_grid
    from ..ops import run_heat
    from ..ops.stencil_pipeline import pick_pipeline_tile, run_heat_pipeline

    interpret = jax.devices()[0].platform != "tpu"
    if dtype == "f64":
        assert jax.config.jax_enable_x64, (
            "heat_sweep(dtype='f64') requires jax_enable_x64 — without it "
            "jnp silently downcasts to f32 and the GB/s column doubles")
    jdt = {"f32": jnp.float32, "f64": jnp.float64}[dtype]
    rows = []
    for n in sizes:
        for order in orders:
            p = SimParams(nx=n, ny=n, order=order, iters=iters)
            u0 = np.asarray(make_initial_grid(p, dtype=jdt))
            cands = [("xla", iters,
                      lambda u: run_heat(u, iters, order, p.xcfl, p.ycfl))]
            if dtype == "f32":
                for k in ks:
                    # round the count down to a multiple of k rather than
                    # silently dropping the kernel from the table
                    it_k = iters - iters % k
                    if not it_k:
                        continue
                    ty = pick_pipeline_tile(p.gy, k, order, width=p.gx)
                    cands.append((f"pipeline-k{k}", it_k,
                                  lambda u, k=k, ty=ty, it=it_k:
                                  run_heat_pipeline(
                                      u, it, order, p.xcfl, p.ycfl, p.bc,
                                      k=k, tile_y=ty, interpret=interpret)))
            for label, n_it, runner in cands:
                cost = heat_cost(n, order=order, iters=n_it, dtype=dtype)
                try:
                    ms = _time_donated_ms(runner, u0)
                except Exception as e:  # sticky per-cell failure = data
                    _raise_if_device_error(e)
                    rows.append({
                        "size": n, "order": order, "kernel": label,
                        "dtype": dtype, "iters": n_it, "ms": -1.0,
                        "gbs": 0.0, "gflops": 0.0,
                        "error": type(e).__name__,
                        "pct_peak": "", "bound": "",
                    })
                    continue
                rows.append({
                    "size": n, "order": order, "kernel": label,
                    "dtype": dtype, "iters": n_it, "ms": round(ms, 2),
                    "gbs": round(cost.gbs(ms), 2),
                    "gflops": round(cost.gflops(ms), 2),
                    "error": "",
                    **_attrib(cost.gbs(ms), cost.gflops(ms)),
                })
    return rows


def transfer_bandwidth_sweep(sizes=(1 << 20, 1 << 24, 1 << 26)) -> list[dict]:
    """Host↔device copy bandwidth (the reference's PCIe measurements,
    ``analysis/PA1_Dong-Bang_Tsai.odt`` §1c — here the PCIe/ICI path to the
    TPU)."""
    import jax
    import jax.numpy as jnp

    rows = []
    dev = jax.devices()[0]
    for n in sizes:
        host = np.random.default_rng(0).integers(
            0, 255, n, dtype=np.uint64).astype(np.uint8)
        jax.device_put(host[:64], dev).block_until_ready()
        t0 = time.perf_counter()
        d = jax.device_put(host, dev)
        d.block_until_ready()
        h2d = time.perf_counter() - t0
        t0 = time.perf_counter()
        _ = np.asarray(d)
        d2h = time.perf_counter() - t0
        rows.append({
            "bytes": n,
            "h2d_gbs": round(n / 1e9 / h2d, 3),
            "d2h_gbs": round(n / 1e9 / d2h, 3),
            # quoted against HBM peak like everything else: an interconnect
            # sitting at low single-digit pct of HBM is the point the
            # reference's PCIe analysis makes
            **_attrib(max(n / 1e9 / h2d, n / 1e9 / d2h)),
        })
    return rows


def pipeline_tune_sweep(size: int = 4000, order: int = 8, iters: int = 64,
                        ks=(1, 2, 4, 8, 16),
                        targets=(256, 192, 128, 64)) -> list[dict]:
    """Tuning table for the pipelined kernels at the HEADLINE shape:
    k (fused sub-steps per HBM pass) × tile_y ladder (VMEM-clamped at the
    grid width) × {1-D full-width, column-tiled} — one capture window
    yields the whole (k, tile) surface behind bench.py's best-kernel
    pick.  Failed cells are rows with an error tag, not aborts."""
    import jax
    import jax.numpy as jnp

    from ..config import SimParams
    from ..core.roofline import heat_cost
    from ..grid import make_initial_grid
    from ..ops.stencil import BORDER_FOR_ORDER
    from ..ops.stencil_pipeline import (pick_pipeline_tile,
                                        run_heat_pipeline,
                                        run_heat_pipeline2d)

    interpret = jax.devices()[0].platform != "tpu"
    p = SimParams(nx=size, ny=size, order=order, iters=iters)
    u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
    rows = []
    for k in ks:
        it_k = iters - iters % k
        if not it_k:
            continue
        tiles = []
        for tgt in targets:
            ty = pick_pipeline_tile(p.gy, k, order, target=tgt, width=p.gx)
            if ty not in tiles:
                tiles.append(ty)
        for ty in tiles:
            cands = [(f"pipeline-k{k}",
                      lambda u, k=k, ty=ty: run_heat_pipeline(
                          u, it_k, order, p.xcfl, p.ycfl, p.bc, k=k,
                          tile_y=ty, interpret=interpret))]
            if k * BORDER_FOR_ORDER[order] <= 128:
                cands.append((f"pipeline2d-k{k}",
                              lambda u, k=k, ty=ty: run_heat_pipeline2d(
                                  u, it_k, order, p.xcfl, p.ycfl, p.bc,
                                  k=k, tile_y=ty, tile_x=512,
                                  interpret=interpret)))
            for name, runner in cands:
                cost = heat_cost(size, order=order, iters=it_k)
                try:
                    ms = _time_donated_ms(runner, u0)
                except Exception as e:  # a failing (k, tile) cell is data
                    _raise_if_device_error(e)
                    rows.append({"kernel": name, "k": k, "tile_y": ty,
                                 "ms": -1.0, "gbs": 0.0, "gflops": 0.0,
                                 "error": type(e).__name__,
                                 "pct_peak": "", "bound": ""})
                    continue
                rows.append({"kernel": name, "k": k, "tile_y": ty,
                             "ms": round(ms, 2),
                             "gbs": round(cost.gbs(ms), 2),
                             "gflops": round(cost.gflops(ms), 2),
                             "error": "",
                             **_attrib(cost.gbs(ms), cost.gflops(ms))})
    return rows


def pallas_tile_sweep(size: int = 2000, order: int = 8, iters: int = 50,
                      tiles=(40, 80, 200, 400)) -> list[dict]:
    """Effective bandwidth vs VMEM tile height for the Pallas stencil — the
    analog of the reference's CUDA block-size sweep
    (``analysis/cipher_bs.cu:154-170``): the knob controlling on-chip
    staging granularity."""
    import jax
    import jax.numpy as jnp

    from ..config import SimParams
    from ..core.roofline import heat_cost
    from ..grid import make_initial_grid
    from ..ops.stencil_pallas import run_heat_pallas

    interpret = jax.devices()[0].platform != "tpu"
    p = SimParams(nx=size, ny=size, order=order, iters=iters)
    u0 = make_initial_grid(p, dtype=jnp.float32)
    cost = heat_cost(size, order=order, iters=iters)
    rows = []
    for t in tiles:
        if size % t:
            continue
        runner = lambda u: run_heat_pallas(u, iters, order, p.xcfl, p.ycfl,
                                           tile_y=t, interpret=interpret)
        ms = _time_donated_ms(runner, u0)
        rows.append({"tile_y": t, "ms": round(ms, 2),
                     "gbs": round(cost.gbs(ms), 2),
                     **_attrib(cost.gbs(ms), cost.gflops(ms))})
    return rows


def heat_kernel_sweep(size: int = 4000, order: int = 8,
                      iters: int = 64, ks=(2, 4, 8),
                      tile: int | None = None) -> list[dict]:
    """Kernel-strategy comparison for the headline stencil: XLA fused
    slices vs one-op conv vs Pallas VMEM band kernel vs k-step temporal
    blocking — the effective-bandwidth table behind bench.py's
    best-kernel pick (reference analog: global vs shared-memory kernels
    in ``data/data.ods``)."""
    import jax
    import jax.numpy as jnp

    from ..config import SimParams
    from ..grid import make_initial_grid
    from ..ops import run_heat, run_heat_conv
    from ..ops.stencil_pallas import (pick_tile, run_heat_multistep,
                                      run_heat_pallas)

    from ..ops.stencil_pipeline import pick_pipeline_tile, run_heat_pipeline

    interpret = jax.devices()[0].platform != "tpu"
    p = SimParams(nx=size, ny=size, order=order, iters=iters)
    u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
    t = tile or pick_tile(p.ny, 200)
    # the conv formulation is ~200× slower per iter; a full-length run
    # would outlive the tunnel's single-execution RPC deadline (the
    # BENCH_r02 failure), so it gets a short run and scaled accounting
    conv_iters = min(iters, 8)

    from ..ops.stencil import run_heat_roll

    cands = {
        "xla": (iters, lambda u: run_heat(u, iters, order, p.xcfl, p.ycfl)),
        "xla-roll": (iters,
                     lambda u: run_heat_roll(u, iters, order, p.xcfl,
                                             p.ycfl, p.bc)),
        "xla-conv": (conv_iters,
                     lambda u: run_heat_conv(u, conv_iters, order, p.xcfl,
                                             p.ycfl)),
        "pallas-roll": (iters,
                        lambda u: run_heat_pallas(u, iters, order, p.xcfl,
                                                  p.ycfl, tile_y=t,
                                                  interpret=interpret)),
    }
    from ..ops.stencil_pipeline import run_heat_pipeline2d

    for k in ks:
        if iters % k == 0:
            cands[f"xla-roll-k{k}"] = (
                iters, lambda u, k=k: run_heat_roll(u, iters, order, p.xcfl,
                                                    p.ycfl, p.bc, k=k))
    for k in (1,) + tuple(ks):
        if iters % k == 0:
            ty = pick_pipeline_tile(p.gy, k, order, width=p.gx)
            cands[f"pipeline-k{k}"] = (
                iters, lambda u, k=k, ty=ty: run_heat_pipeline(
                    u, iters, order, p.xcfl, p.ycfl, p.bc, k=k, tile_y=ty,
                    interpret=interpret))
            cands[f"pipeline2d-k{k}"] = (
                iters, lambda u, k=k, ty=ty: run_heat_pipeline2d(
                    u, iters, order, p.xcfl, p.ycfl, p.bc, k=k, tile_y=ty,
                    tile_x=512, interpret=interpret))
    for k in ks:
        if iters % k == 0:
            cands[f"pallas-k{k}"] = (
                iters, lambda u, k=k: run_heat_multistep(
                    u, iters, order, p.xcfl, p.ycfl, p.bc, k=k, tile_y=t,
                    interpret=interpret))

    from ..core.roofline import heat_cost

    rows = []
    for name, (n_it, fn) in cands.items():
        cost = heat_cost(size, order=order, iters=n_it)
        try:
            ms = _time_donated_ms(fn, u0)  # same-iters warmup inside
        except Exception as e:  # a kernel variant failing to lower is data
            _raise_if_device_error(e)
            rows.append({"kernel": name, "ms": -1.0, "gbs": 0.0,
                         "error": type(e).__name__,
                         "pct_peak": "", "bound": ""})
            continue
        rows.append({"kernel": name, "ms": round(ms, 2),
                     "gbs": round(cost.gbs(ms), 2),
                     "error": "",
                     **_attrib(cost.gbs(ms), cost.gflops(ms))})
    return rows


def sort_thread_sweep(num_elements: int = 1_000_000,
                      threads=(1, 2, 4, 8, 16, 32)) -> list[dict]:
    from .. import native

    rng = np.random.default_rng(0)
    mkeys = rng.integers(-(2**31), 2**31, num_elements,
                         dtype=np.int64).astype(np.int32)
    rkeys = rng.integers(0, 2**32, num_elements,
                         dtype=np.uint64).astype(np.uint32)
    # warm up: build/load the library and touch the buffers so the first
    # timed row doesn't carry compile + page-fault cost
    native.merge_sort(mkeys[:10_000].copy())
    native.radix_sort(rkeys[:10_000].copy())
    from ..core.roofline import sort_cost

    rows = []
    for t in threads:
        native.set_threads(t)
        a = mkeys.copy()
        t0 = time.perf_counter()
        native.merge_sort(a)
        t_merge = time.perf_counter() - t0
        b = rkeys.copy()
        t0 = time.perf_counter()
        native.radix_sort(b)
        t_radix = time.perf_counter() - t0
        merge_gbs = sort_cost(num_elements, "merge").nbytes / 1e9 / t_merge
        radix_gbs = sort_cost(num_elements, "radix").nbytes / 1e9 / t_radix
        rows.append({
            "threads": t,
            "merge_s": round(t_merge, 4),
            "radix_elems_per_s": round(num_elements / t_radix, 0),
            **_attrib(max(merge_gbs, radix_gbs)),
        })
    return rows


def sort_sweep(ns=(1 << 16, 1 << 20),
               kernels=("lax", "radix", "bitonic", "auto")) -> list[dict]:
    """TPU-resident sorts vs size: the ``lax.sort`` library path, the
    4-phase radix, the bitonic network, and the tuned ``auto`` dispatch
    (``ops.sort.sort_auto``) — the crossover table ``tune run --op sort``
    measures, re-read here as data.  Byte accounting via
    ``roofline.sort_cost`` (radix: 4 scatter passes; merge/bitonic:
    log2(n) compare-exchange passes); every row carries ``pct_peak`` /
    ``bound`` and the ``tuned`` column names the cached winner the auto
    row dispatched to (empty: no winner cached, auto == lax)."""
    import jax.numpy as jnp

    from ..core import programs, tune
    from ..core.roofline import sort_cost
    # NOT ``from ..ops import sort``: the package re-exports the sort
    # *function* under that name, shadowing the submodule attribute
    from ..ops.sort import bitonic_sort, radix_sort, sort, sort_auto

    fns = {"lax": sort, "radix": radix_sort,
           "bitonic": bitonic_sort, "auto": sort_auto}
    rows = []
    for n in ns:
        rng = np.random.default_rng(n % 97)
        keys_host = rng.integers(0, 2 ** 32, n, dtype=np.uint32)
        keys = jnp.asarray(keys_host)
        expect = np.sort(keys_host)
        rec = tune.lookup("sort", f"n{programs.canonical_size(n)}", "uint32")
        tuned = rec["candidate"] if rec else ""
        for kernel in kernels:
            resolved = tuned or "lax" if kernel == "auto" else kernel
            cost = sort_cost(n, kind="radix" if resolved == "radix"
                             else "merge")
            try:
                ms = _time_ms(fns[kernel], keys)
                ok = bool((np.asarray(fns[kernel](keys)) == expect).all())
            except Exception as e:  # a kernel failing at a size is data
                _raise_if_device_error(e)
                rows.append({"n": n, "kernel": kernel, "tuned": tuned,
                             "ms": -1.0, "gbs": 0.0, "ok": False,
                             "error": type(e).__name__,
                             "pct_peak": "", "bound": ""})
                continue
            rows.append({"n": n, "kernel": kernel, "tuned": tuned,
                         "ms": round(ms, 3),
                         "gbs": round(cost.gbs(ms), 3), "ok": ok,
                         "error": "", **_attrib(cost.gbs(ms))})
    return rows


def dist_heat_sweep(size: int = 256, order: int = 8, iters: int = 20,
                    ndevs=(1, 2, 4, 8),
                    pallas: bool | None = None) -> list[dict]:
    """Strong-scaling table for the distributed heat solver: device count ×
    {1D stripes, 2D blocks} × {sync, overlapped} — the hw5 measurement grid
    (``hw/hw5/programming/data.ods``; BASELINE.md hw5 table).

    ``pallas`` adds the tuned per-shard-kernel scheme (``pallas-k4``).
    Default (None): only on TPU, where the kernel is compiled — off-TPU it
    runs in interpret mode, slow enough that the row is opt-in (the CPU
    stand-in capture opts in so the scaling table carries the scheme the
    TPU capture measures).
    """
    import jax

    from ..config import GridMethod, SimParams
    from ..core.roofline import heat_cost
    from ..dist import mesh_for_method, prepare_distributed_heat

    rows = []
    avail = len(jax.devices())
    schemes = [("sync", False, 1, "xla"), ("async", True, 1, "xla"),
               ("ca-k4", False, 4, "xla")]
    if pallas is None:
        pallas = jax.devices()[0].platform == "tpu"
    if pallas:
        schemes.append(("pallas-k4", False, 4, "pallas"))
    for nd in ndevs:
        if nd > avail:
            continue
        for method in (GridMethod.STRIPES_1D, GridMethod.BLOCKS_2D):
            for requested, overlap, k, lk in schemes:
                p = SimParams(nx=size, ny=size, order=order, iters=iters)
                mesh = mesh_for_method(method, nd)
                iterate, used_overlap, used_k = prepare_distributed_heat(
                    p, mesh, overlap=overlap, steps_per_exchange=k,
                    local_kernel=lk)
                iterate()          # warmup: same iters → same executable
                secs, _ = iterate()  # device loop only (MPI_Wtime analog)
                # record the scheme that actually ran: overlap and the
                # communication-avoiding path fall back when shards are
                # too thin (or iters doesn't divide)
                if used_k > 1:
                    scheme = f"ca-k{used_k}"
                elif used_overlap:
                    scheme = "async"
                else:
                    scheme = "sync"
                cost = heat_cost(size, order=order, iters=iters)
                rows.append({
                    "devices": nd,
                    "method": "1D" if method == GridMethod.STRIPES_1D else "2D",
                    "scheme": scheme,
                    "requested": requested,
                    "local_kernel": lk,
                    # interpret-mode Pallas times the interpreter, not the
                    # kernel — the column keeps any such row from being
                    # read as a timing (off-TPU captures should prefer
                    # dist_heat_compile_coverage for the pallas scheme)
                    "mode": ("interpret" if lk == "pallas"
                             and jax.devices()[0].platform != "tpu"
                             else "compiled"),
                    "seconds": round(secs, 4),
                    # aggregate effective bandwidth across the gang: the
                    # strong-scaling view the hw5 tables quote
                    "gbs": round(cost.gbs(secs * 1e3), 2),
                    **_attrib(cost.gbs(secs * 1e3),
                              cost.gflops(secs * 1e3)),
                })
    return rows


def dist_heat_compile_coverage(size: int = 2000, order: int = 8,
                               iters: int = 4,
                               ndevs=(1, 2, 4, 8)) -> list[dict]:
    """Compile-coverage matrix for the tuned per-shard Pallas scheme under
    every mesh shape — NOT a timing table.

    Off-TPU the per-shard kernel runs in the Pallas interpreter, 40-80×
    slower than the compiled kernel, so "does it build and run under this
    mesh shape" evidence lives here (few iterations, ``ok`` column)
    instead of inside the ``dist_heat_scaling`` timing CSV where an
    interpreter row reads like a 40× regression.
    """
    import jax

    from ..config import GridMethod, SimParams
    from ..dist import mesh_for_method, prepare_distributed_heat

    mode = ("compiled" if jax.devices()[0].platform == "tpu"
            else "interpret")
    rows = []
    for nd in ndevs:
        if nd > len(jax.devices()):
            continue
        for method in (GridMethod.STRIPES_1D, GridMethod.BLOCKS_2D):
            p = SimParams(nx=size, ny=size, order=order, iters=iters)
            mesh = mesh_for_method(method, nd)
            try:
                iterate, _, used_k = prepare_distributed_heat(
                    p, mesh, overlap=False, steps_per_exchange=4,
                    local_kernel="pallas")
                iterate()
                ok, err = True, ""
                scheme = f"ca-k{used_k}" if used_k > 1 else "sync"
            except Exception as e:  # noqa: BLE001 — coverage, not timing
                _raise_if_device_error(e)
                ok, err, scheme = False, f"{type(e).__name__}: {e}", ""
            rows.append({
                "devices": nd,
                "method": "1D" if method == GridMethod.STRIPES_1D else "2D",
                "scheme": scheme, "local_kernel": "pallas", "mode": mode,
                "iters": iters, "ok": ok, "error": err,
                "pct_peak": "", "bound": "",  # coverage table, not timing
            })
    return rows


def scan_sweep(n: int = 1 << 26, num_segments: int = 1 << 16) -> list[dict]:
    """Effective bandwidth of the scan family at 2^26 floats: plain
    inclusive scan, segmented scan, and the tiled transpose (the
    "transpose+scan eff. GB/s" metrics)."""
    import jax
    import jax.numpy as jnp

    from ..core.roofline import scan_cost, transpose_cost
    from ..ops import inclusive_scan, segmented_scan, transpose_pallas, transpose_xla
    from ..ops.segmented import head_flags_from_starts

    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.standard_normal(n).astype(np.float32))
    starts = np.sort(rng.choice(np.arange(1, n, dtype=np.int64),
                                size=num_segments - 1, replace=False))
    starts = np.concatenate([[0], starts]).astype(np.int32)
    flags = head_flags_from_starts(jnp.asarray(starts), n)

    rows = []
    cost = scan_cost(n)
    ms = _time_ms(jax.jit(inclusive_scan), v)
    rows.append({"op": "inclusive_scan", "n": n, "ms": round(ms, 2),
                 "gbs": round(cost.gbs(ms), 2), **_attrib(cost.gbs(ms))})
    ms = _time_ms(jax.jit(segmented_scan), v, flags)
    rows.append({"op": "segmented_scan", "n": n, "ms": round(ms, 2),
                 "gbs": round(cost.gbs(ms), 2), **_attrib(cost.gbs(ms))})

    side = 4096
    m = jnp.asarray(rng.standard_normal((side, side)).astype(np.float32))
    interpret = jax.devices()[0].platform != "tpu"
    tcost = transpose_cost(side, side)
    for name, fn in [("transpose_xla", lambda x: transpose_xla(x)),
                     ("transpose_pallas", lambda x: transpose_pallas(
                         x, tile=256, interpret=interpret))]:
        ms = _time_ms(fn, m)
        rows.append({"op": name, "n": side * side, "ms": round(ms, 2),
                     "gbs": round(tcost.gbs(ms), 2),
                     **_attrib(tcost.gbs(ms))})
    return rows


def spmv_scan_sweep(ns=(1 << 16, 1 << 20, 1 << 22), iters: int = 8,
                    kernels=None, p_frac: float = 0.01) -> list[dict]:
    """Effective bandwidth of the iterated SpMV-scan engine vs kernel and
    problem size — the flat-vs-blocked-vs-fused comparison behind the
    O(n) scan work (ISSUE 1).

    Byte accounting is exact (``apps/spmv_scan.bytes_moved``): every
    kernel is quoted against the single-pass useful-byte count, so the
    GB/s column directly exposes the flat sweep's log2(n) extra traffic.
    ``kernels=None`` picks all four on TPU but only the XLA pair
    elsewhere — the Pallas kernels in interpret mode at multi-million n
    would take hours (they still appear via ``spmv_pallas_coverage``).
    """
    import jax

    from ..apps import spmv_scan as sp
    from ..core import PhaseTimer

    if kernels is None:
        kernels = (("flat", "blocked", "pallas", "pallas-fused")
                   if jax.devices()[0].platform == "tpu"
                   else ("flat", "blocked"))
    from ..core import programs, tune
    from ..core.roofline import spmv_scan_cost

    rows = []
    for n in ns:
        p = max(3, int(n * p_frac))
        prob = sp.generate_problem(n, p, max(2, p - 1), iters=iters,
                                   seed=n % 97)
        cost = spmv_scan_cost(n, iters)
        # the cached autotuner winner for this size class, as a column:
        # rows from a tuned capture say which config dispatch would pick
        rec = tune.lookup("spmv_scan", f"n{programs.canonical_size(n)}")
        tuned = rec["candidate"] if rec else ""
        for kernel in kernels:
            timer = PhaseTimer()
            try:
                # fallback off: a kernel failing at this shape must surface as a
                # data row (or coverage failure), not silently demote
                out = sp.run_spmv_scan(prob, timer=timer, kernel=kernel,
                                       fallback=False)
            except Exception as e:  # a kernel failing at a shape is data
                _raise_if_device_error(e)
                rows.append({"n": n, "p": p, "iters": iters,
                             "kernel": kernel, "tuned": tuned,
                             "ms": -1.0, "gbs": 0.0,
                             "rel_l2": "", "error": type(e).__name__,
                             "pct_peak": "", "bound": ""})
                continue
            errs = sp.external_check(prob, out)
            ms = timer.last_ms("spmv_scan")
            rows.append({"n": n, "p": p, "iters": iters, "kernel": kernel,
                         "tuned": tuned, "ms": round(ms, 3),
                         "gbs": round(cost.gbs(ms), 3),
                         "rel_l2": f"{errs['rel_l2']:.2e}", "error": "",
                         **_attrib(cost.gbs(ms), cost.gflops(ms))})
    return rows


def spmv_pallas_coverage(names=None, scale: float = 1.0,
                         iters: int = 1) -> list[dict]:
    """Shape-coverage rehearsal for the Pallas segmented-scan kernel at
    full suite sizes — NOT a timing table.

    The kernel's first timed suite run must not be its first run at suite
    shapes (round-4 review finding: its tests cover small shapes only).
    Off-TPU this exercises every instance's padded tile geometry through
    the Pallas interpreter and checks the output against the flat-XLA
    kernel; on TPU the same rows double as a cheap per-shape compile
    check before device minutes are spent on the timed suite.
    """
    import dataclasses

    import jax

    from ..apps import spmv_scan as sp
    from ..apps.matrix_market import real_instance_specs

    mode = ("compiled" if jax.devices()[0].platform == "tpu"
            else "interpret")
    specs = [(n, "synthetic", None)
             for n in (names or sp.BELL_GARLAND_SUITE)]
    if names is None:
        specs.extend(real_instance_specs())
    rows = []
    for name, source, factory in specs:
        prob = (sp.suite_problem(name, scale=scale) if factory is None
                else factory())
        prob = dataclasses.replace(prob, iters=iters)
        rel = None
        try:
            out_pallas = sp.run_spmv_scan(prob, kernel="pallas-fused",
                                          fallback=False)
            out_flat = sp.run_spmv_scan(prob, kernel="flat",
                                        fallback=False)
            rel = float(np.linalg.norm(out_pallas - out_flat)
                        / max(np.linalg.norm(out_flat), 1e-30))
            ok, err = bool(rel < 1e-4), ""
        except Exception as e:  # noqa: BLE001 — coverage, not timing
            _raise_if_device_error(e)
            ok, err = False, f"{type(e).__name__}: {e}"
        rows.append({
            "matrix": name, "source": source, "n": prob.n, "p": prob.p,
            "mode": mode, "iters": iters, "ok": ok,
            "rel_l2_vs_flat": f"{rel:.2e}" if rel is not None else "",
            "error": err,
            "pct_peak": "", "bound": "",  # coverage table, not timing
        })
        print(rows[-1])
    return rows


def spmv_suite_sweep(names=None, scale: float = 0.05,
                     kernels=None, cpu_threads: int | None = 4) -> list[dict]:
    """Device kernels vs the OpenMP CPU reference over the suite.

    ``cpu_threads`` adds the reference's CPU measurement axis (4-thread
    table, ``hw/hw_final/programming/data.ods`` table 2 / ``fp.cu:130-152``)
    as a ``cpu_ms`` column; ``None`` skips it.  ``kernels=None`` picks
    ``("flat", "blocked", "pallas-fused")`` on TPU but ``("flat",)``
    elsewhere — the Pallas segmented kernel in interpret mode at suite
    scale would take hours.
    """
    import jax

    from .. import native
    from ..apps import spmv_scan as sp
    from ..core import PhaseTimer
    from ..core.roofline import spmv_scan_cost

    if kernels is None:
        kernels = (("flat", "blocked", "pallas-fused")
                   if jax.devices()[0].platform == "tpu" else ("flat",))

    rows = []
    specs = [(n, "synthetic", None)
             for n in (names or sp.BELL_GARLAND_SUITE)]
    # on the full default suite, the shipped/reconstructed real-matrix
    # instances (HB/gr_30_30, Williams/dense2) ride the same sweep so the
    # table has rows whose source is a real published problem, not a
    # suite-shaped synthetic; an explicit names subset stays that subset
    from ..apps.matrix_market import real_instance_specs
    if names is None:
        specs.extend(real_instance_specs())
    for name, source, factory in specs:
        if source == "synthetic":
            prob = sp.suite_problem(name, scale=scale)
        else:
            prob = factory()
        cpu_ms = None
        if cpu_threads is not None:
            prev = native.thread_count()
            try:
                native.set_threads(cpu_threads)
                native.spmv_scan_cpu(prob.a, prob.s[:-1], prob.xx, 1)  # warm
                t0 = time.perf_counter()
                native.spmv_scan_cpu(prob.a, prob.s[:-1], prob.xx,
                                     prob.iters)
                cpu_ms = (time.perf_counter() - t0) * 1e3
            finally:
                native.set_threads(prev)
        for kernel in kernels:
            timer = PhaseTimer()
            # fallback off: a failing kernel must fail this timing row,
            # not silently demote to (and time) a different kernel
            out = sp.run_spmv_scan(prob, timer=timer, kernel=kernel,
                                   fallback=False)
            errs = sp.external_check(prob, out)
            cost = spmv_scan_cost(prob.n, prob.iters)
            ms = timer.last_ms("spmv_scan")
            row = {
                "matrix": name, "source": source, "kernel": kernel,
                "n": prob.n, "p": prob.p, "iters": prob.iters,
                "ms": round(ms, 3),
                "gbs": round(cost.gbs(ms), 3),
                "rel_l2": f"{errs['rel_l2']:.2e}",
                **_attrib(cost.gbs(ms), cost.gflops(ms)),
            }
            if cpu_ms is not None:
                row["cpu_ms"] = round(cpu_ms, 3)
                row["cpu_threads"] = cpu_threads
            rows.append(row)
    return rows
