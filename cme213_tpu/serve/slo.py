"""Multi-window burn-rate SLO monitoring for the serving front end.

Degraded mode (``serve/server.py``) originally triggered on raw queue
depth — a capacity symptom, not an objective.  This module watches the
objectives themselves, SRE-style: each declarative :class:`Objective`
(p99 latency bound, shed-rate budget, error-rate budget) is evaluated
over a rolling **short** and **long** window, and the *burn rate* — how
fast the error budget is being consumed relative to plan — must exceed
the threshold in **both** windows before the monitor fires.  The
two-window AND is the standard flap filter: the long window proves the
problem is sustained, the short window proves it is still happening.

Burn semantics:

- ``p99_latency_ms``: ``target`` is the latency bound; the budget is the
  allowed fraction of served requests over the bound (default 1%).
  burn = (fraction over bound) / budget — burn 1.0 means exactly
  on-budget, 2.0 means consuming budget twice as fast as allowed.
- ``shed_rate`` / ``error_rate``: ``target`` *is* the budget fraction;
  burn = observed rate / target.
- ``drift_rate``: ``target`` is the allowed fraction of *shadow
  conformance samples* (``core/numerics.py``) over their drift
  tolerance; burn = observed over-tolerance rate / target, evaluated
  over the shadow samples only.  This is the fleet-level view of the
  same signal the per-(op, rung) drift budget demotes rungs on.

Transitions are evented (``slo-burn`` on entry, ``slo-ok`` on recovery)
and the worst short-window burn is exported as the ``serve.slo.burn``
gauge.  Recovery has hysteresis — the short burn must fall to
``threshold * hysteresis`` (default half) before ``slo-ok`` fires — so
the monitor cannot flap on a burn hovering at the threshold.  All timing
comes from an injectable ``core.resilience.Clock``; under a
``VirtualClock`` the whole fire/recover cycle is testable without a
wall-clock sleep.

The server consumes :attr:`SLOMonitor.burning` as a degraded-mode
trigger (checked before the raw depth/p99 triggers — objective violation
is the primary signal; depth is the backstop).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from ..core import metrics
from ..core.resilience import Clock
from ..core.trace import record_event

#: objective kinds (see module docstring for burn semantics)
KINDS = ("p99_latency_ms", "shed_rate", "error_rate", "drift_rate")


@dataclass(frozen=True)
class Objective:
    """One declarative service-level objective."""

    name: str                 # stable key for events/reporting
    kind: str                 # one of KINDS
    target: float             # latency bound (ms) or budget fraction
    budget: float = 0.01      # p99_latency_ms only: allowed over-bound frac

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown objective kind {self.kind!r} "
                             f"(expected one of {KINDS})")
        if self.target <= 0:
            raise ValueError(f"objective target must be > 0, got {self.target}")


class SLOMonitor:
    """Rolling-window burn-rate evaluation over per-request outcomes.

    ``observe()`` one sample per finished request (served, shed, or
    failed); ``evaluate()`` once per scheduling step.  Samples older than
    the long window are pruned, so memory is bounded by arrival rate ×
    ``long_window_s``.
    """

    def __init__(self, objectives, clock: Clock | None = None,
                 short_window_s: float = 5.0, long_window_s: float = 60.0,
                 burn_threshold: float = 2.0, min_samples: int = 10,
                 hysteresis: float = 0.5):
        self.objectives = list(objectives)
        self.clock = clock if clock is not None else Clock()
        self.short_window_s = short_window_s
        self.long_window_s = max(long_window_s, short_window_s)
        self.burn_threshold = burn_threshold
        self.min_samples = max(1, min_samples)
        self.hysteresis = hysteresis
        #: (t, latency_ms | None, shed, failed, drift | None) per
        #: finished request; ``drift`` is None unless the request was a
        #: shadow conformance sample (then True = over tolerance)
        self._samples: deque = deque()
        self._burning: dict[str, bool] = {o.name: False
                                          for o in self.objectives}
        self._last: dict[str, dict] = {}

    # ------------------------------------------------------------ intake

    def observe(self, latency_ms: float | None = None,
                shed: bool = False, failed: bool = False,
                drift: bool | None = None) -> None:
        """Record one finished request (call with the served latency, or
        ``shed=True`` / ``failed=True``; ``drift`` carries a shadow
        conformance sample's over-tolerance verdict when the request was
        sampled)."""
        self._samples.append(
            (self.clock.now(), latency_ms, bool(shed), bool(failed),
             drift if drift is None else bool(drift)))

    def observe_result(self, result) -> None:
        """``observe()`` from a :class:`~.request.SolveResult`."""
        from .request import FAILED, SHED
        self.observe(latency_ms=result.latency_ms,
                     shed=result.status == SHED,
                     failed=result.status == FAILED)

    # -------------------------------------------------------- evaluation

    def _burn(self, objective: Objective, window) -> float | None:
        """Burn rate of one objective over one sample window; None when
        the window has no relevant samples."""
        if objective.kind == "p99_latency_ms":
            lat = [s[1] for s in window if s[1] is not None and not s[2]]
            if not lat:
                return None
            over = sum(1 for v in lat if v > objective.target) / len(lat)
            return over / objective.budget
        if objective.kind == "drift_rate":
            shadow = [s[4] for s in window if s[4] is not None]
            if not shadow:
                return None
            return (sum(1 for v in shadow if v) / len(shadow)
                    / objective.target)
        if not window:
            return None
        if objective.kind == "shed_rate":
            rate = sum(1 for s in window if s[2]) / len(window)
        else:  # error_rate
            rate = sum(1 for s in window if s[3]) / len(window)
        return rate / objective.target

    def evaluate(self) -> dict:
        """Prune, recompute burns, fire transition events, update the
        ``serve.slo.burn`` gauge.  Returns per-objective state (also kept
        for :meth:`state`)."""
        now = self.clock.now()
        while self._samples and self._samples[0][0] < now - self.long_window_s:
            self._samples.popleft()
        long_win = list(self._samples)
        short_win = [s for s in long_win if s[0] >= now - self.short_window_s]

        worst_short = 0.0
        out: dict[str, dict] = {}
        for o in self.objectives:
            burn_short = self._burn(o, short_win)
            burn_long = self._burn(o, long_win)
            if burn_short is not None:
                worst_short = max(worst_short, burn_short)
            was_burning = self._burning[o.name]
            if (not was_burning
                    and burn_short is not None and burn_long is not None
                    and len(short_win) >= self.min_samples
                    and burn_short >= self.burn_threshold
                    and burn_long >= self.burn_threshold):
                self._burning[o.name] = True
                record_event("slo-burn", objective=o.name,
                             burn_short=round(burn_short, 3),
                             burn_long=round(burn_long, 3),
                             threshold=self.burn_threshold)
            elif (was_burning
                  and (burn_short is None
                       or burn_short <= self.burn_threshold * self.hysteresis)):
                self._burning[o.name] = False
                record_event("slo-ok", objective=o.name,
                             burn_short=round(burn_short, 3)
                             if burn_short is not None else 0.0)
            out[o.name] = {
                "kind": o.kind,
                "target": o.target,
                "burn_short": (round(burn_short, 3)
                               if burn_short is not None else None),
                "burn_long": (round(burn_long, 3)
                              if burn_long is not None else None),
                "burning": self._burning[o.name],
            }
        metrics.gauge("serve.slo.burn").set(round(worst_short, 3))
        self._last = out
        return out

    @property
    def burning(self) -> bool:
        """True while any objective is in the burning state."""
        return any(self._burning.values())

    def state(self) -> dict:
        """Last :meth:`evaluate` result (for reports); ``{}`` before the
        first evaluation."""
        return dict(self._last)


def from_flags(clock: Clock | None = None, *,
               p99_ms: float | None = None, shed_rate: float | None = None,
               error_rate: float | None = None,
               drift_rate: float | None = None, short_s: float = 5.0,
               long_s: float = 60.0, burn_threshold: float = 2.0,
               min_samples: int = 10) -> SLOMonitor | None:
    """Build a monitor from CLI-flag values; None when no objective was
    requested (the server then runs without an SLO hook)."""
    objectives = []
    if p99_ms is not None:
        objectives.append(Objective("p99-latency", "p99_latency_ms", p99_ms))
    if shed_rate is not None:
        objectives.append(Objective("shed-rate", "shed_rate", shed_rate))
    if error_rate is not None:
        objectives.append(Objective("error-rate", "error_rate", error_rate))
    if drift_rate is not None:
        objectives.append(Objective("drift-rate", "drift_rate", drift_rate))
    if not objectives:
        return None
    return SLOMonitor(objectives, clock=clock, short_window_s=short_s,
                      long_window_s=long_s, burn_threshold=burn_threshold,
                      min_samples=min_samples)
