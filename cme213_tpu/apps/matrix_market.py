"""MatrixMarket → SpMV-scan problem instances (the readMM.py parity path).

The reference's dataset generators (``hw/hw_final/programming/aux/readMM.py``,
``aux/fileReadMM.py``) read SuiteSparse ``.mtx`` files with SciPy and emit
``a.txt``/``x.txt`` instances: ``a`` = the nonzero values, ``s`` = a random
sorted subset of indices (with 0/n sentinels), ``k`` = random gather indices,
``x`` = uniform(−1,1), ``N`` ∈ [5,100].  This module does the same with a
dependency-free coordinate-format parser, so real SuiteSparse matrices can be
fed to the engine when available.
"""

from __future__ import annotations

import gzip
import warnings

import numpy as np

from ..core.errors import data_error
from .spmv_scan import Problem


def read_matrix_market(path: str):
    """Minimal MatrixMarket coordinate parser, hardened at the boundary.

    Supports ``matrix coordinate (real|integer|pattern) (general|symmetric)``.
    Returns (rows, cols, values, shape) with 0-based indices, symmetric
    entries expanded.

    Every ingestion invariant is checked here — header/banner shape, the
    size line, entry count vs the declared nnz (truncated downloads), the
    per-entry column arity, 1-based index bounds, value finiteness — and a
    violation raises a structured :class:`core.errors.DataValidationError`
    (with a ``data-validation`` trace event) instead of shipping garbage
    into the SpMV engine, where a bad index would surface as a silent
    out-of-bounds gather clamp.
    """
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        header = f.readline().strip().lower().split()
        if header[:2] != ["%%matrixmarket", "matrix"]:
            raise data_error(path, "banner",
                             "not a MatrixMarket matrix file")
        if len(header) < 5:
            raise data_error(path, "banner",
                             f"truncated banner ({' '.join(header)!r})")
        if header[2] != "coordinate":
            raise data_error(path, "format",
                             f"only coordinate format supported, "
                             f"got {header[2]!r}")
        field, sym = header[3], header[4]
        if field not in ("real", "integer", "pattern"):
            raise data_error(path, "field", f"unsupported field {field!r}")
        if sym not in ("general", "symmetric"):
            raise data_error(path, "symmetry",
                             f"unsupported symmetry {sym!r}")
        line = f.readline()
        while line.startswith("%"):
            line = f.readline()
        try:
            nr, nc, nnz = (int(v) for v in line.split())
        except ValueError as e:
            raise data_error(path, "size-line",
                             f"bad size line {line.strip()!r}: {e}") from e
        if nr <= 0 or nc <= 0 or nnz < 0:
            raise data_error(path, "size-line",
                             f"non-positive dims/count ({nr}, {nc}, {nnz})")
        try:
            with warnings.catch_warnings():
                # a file truncated to zero entries triggers np.loadtxt's
                # empty-input UserWarning; that case is data, not noise —
                # it flows into the entry-count DataValidationError below
                warnings.simplefilter("ignore", UserWarning)
                data = np.loadtxt(f, ndmin=2)
        except ValueError as e:
            raise data_error(path, "entries",
                             f"unparseable entry data: {e}") from e
    want_cols = 2 if field == "pattern" else 3
    if nnz == 0:
        data = data.reshape(0, want_cols)
    if data.shape[0] != nnz:
        raise data_error(path, "entry-count",
                         f"header declares {nnz} entries, file holds "
                         f"{data.shape[0]} (truncated or padded file)")
    if nnz and data.shape[1] < want_cols:
        raise data_error(path, "entry-arity",
                         f"{field} entries need {want_cols} columns, "
                         f"got {data.shape[1]}")
    rows = data[:, 0].astype(np.int64) - 1
    cols = data[:, 1].astype(np.int64) - 1
    if nnz and (not np.all(data[:, :2] == np.floor(data[:, :2]))):
        raise data_error(path, "index-integrality",
                         "fractional row/col index")
    if ((rows < 0) | (rows >= nr)).any() or ((cols < 0) | (cols >= nc)).any():
        raise data_error(path, "index-bounds",
                         f"row/col index outside 1..{nr} x 1..{nc}")
    if field == "pattern":
        vals = np.ones(rows.shape[0], dtype=np.float32)
    else:
        vals = data[:, 2].astype(np.float32)
        if not np.isfinite(vals).all():
            raise data_error(path, "value-finiteness",
                             "non-finite (nan/inf) matrix value")
    if sym == "symmetric":
        if ((rows < cols).any()):
            raise data_error(path, "symmetry",
                             "symmetric file stores an upper-triangle "
                             "entry (lower triangle expected)")
        off = rows != cols
        rows, cols = (np.concatenate([rows, cols[off]]),
                      np.concatenate([cols, rows[off]]))
        vals = np.concatenate([vals, vals[off]])
    return rows, cols, vals, (nr, nc)


def coo_to_csr(rows, cols, vals, shape):
    """(indptr, indices, data) in canonical CSR (row-major, columns sorted
    within each row) from validated COO triplets."""
    nr, _ = shape
    order = np.lexsort((cols, rows))
    rows, cols, vals = rows[order], cols[order], vals[order]
    indptr = np.zeros(nr + 1, dtype=np.int64)
    np.add.at(indptr, rows + 1, 1)
    np.cumsum(indptr, out=indptr)
    return indptr, cols, vals


def validate_csr(indptr, indices, data, shape, source: str = "csr") -> None:
    """CSR structural invariants, raising structured
    :class:`DataValidationError` on the first violation: ``indptr`` is
    monotone non-decreasing with ``indptr[0] == 0`` and
    ``indptr[-1] == nnz``; column indices are in-bounds; values finite."""
    nr, nc = shape
    if indptr.shape[0] != nr + 1:
        raise data_error(source, "indptr-length",
                         f"len(indptr)={indptr.shape[0]}, rows+1={nr + 1}")
    if indptr[0] != 0:
        raise data_error(source, "indptr-origin",
                         f"indptr[0]={indptr[0]} != 0")
    if (np.diff(indptr) < 0).any():
        raise data_error(source, "indptr-monotone",
                         "indptr decreases (overlapping rows)")
    if indptr[-1] != indices.shape[0] or indices.shape[0] != data.shape[0]:
        raise data_error(source, "nnz-consistency",
                         f"indptr[-1]={indptr[-1]}, len(indices)="
                         f"{indices.shape[0]}, len(data)={data.shape[0]}")
    if indices.size and (((indices < 0) | (indices >= nc)).any()):
        raise data_error(source, "column-bounds",
                         f"column index outside 0..{nc - 1}")
    if not np.isfinite(data).all():
        raise data_error(source, "value-finiteness",
                         "non-finite (nan/inf) CSR value")


def csr_from_mtx(path: str):
    """Load ``path`` straight to validated canonical CSR:
    ``(indptr, indices, data, shape)``.  Both the COO-level ingestion
    checks (``read_matrix_market``) and the CSR structural invariants
    (``validate_csr``) have passed when this returns."""
    rows, cols, vals, shape = read_matrix_market(path)
    indptr, indices, data = coo_to_csr(rows, cols, vals, shape)
    validate_csr(indptr, indices, data, shape, source=path)
    return indptr, indices, data, shape


def gr_30_30_mtx() -> str:
    """Reconstruct SuiteSparse ``HB/gr_30_30`` as MatrixMarket text.

    The published problem is exactly defined: the nine-point star
    discretization of the Laplacian on a 30×30 grid (n = 900,
    nnz = 7744 expanded — 900 diagonal + 6844 king-graph adjacencies),
    symmetric.  This environment has no network access, so the framework
    ships this *reconstruction* instead of the downloaded file: the
    nonzero pattern is forced by the discretization and matches the
    SuiteSparse instance; values use the standard 9-point star
    coefficients (8 on the diagonal, −1 for the eight neighbours).
    Stored as symmetric/lower like the original HB-derived .mtx
    (4322 stored entries), which also exercises the reader's symmetric
    expansion path.
    """
    side = 30
    entries = []  # (row, col, value) 1-based, lower triangle
    for i in range(side):
        for j in range(side):
            r = i * side + j
            entries.append((r + 1, r + 1, 8.0))
            for di in (-1, 0, 1):
                for dj in (-1, 0, 1):
                    if di == 0 and dj == 0:
                        continue
                    ni, nj = i + di, j + dj
                    if not (0 <= ni < side and 0 <= nj < side):
                        continue
                    c = ni * side + nj
                    if c < r:  # store lower triangle only
                        entries.append((r + 1, c + 1, -1.0))
    entries.sort(key=lambda e: (e[1], e[0]))  # column-major like HB files
    n = side * side
    lines = [
        "%%MatrixMarket matrix coordinate real symmetric",
        "% HB/gr_30_30 — nine-point star discretization on a 30x30 grid.",
        "% Reconstructed from the published problem definition (no network",
        "% access in this environment): pattern is exactly the SuiteSparse",
        "% instance's (n=900, nnz=7744 expanded); values are the standard",
        "% 9-point star coefficients.",
        f"{n} {n} {len(entries)}",
    ]
    lines += [f"{r} {c} {v:.1f}" for r, c, v in entries]
    return "\n".join(lines) + "\n"


def gr_30_30_path() -> str:
    """Path of the shipped real-matrix instance (examples/gr_30_30.mtx)."""
    import os

    return os.path.join(os.path.dirname(__file__), "..", "..", "examples",
                        "gr_30_30.mtx")


def dense2_problem(iters: int | None = 10, seed: int = 0) -> Problem:
    """Reconstruct the suite's ``Williams/dense2`` instance.

    The published problem (named in ``aux/reference_spMVscan-released.cu:
    168-185``) is a literal dense 2000×2000 matrix stored in sparse
    format, so its nonzero pattern is fully determined: all 4,000,000
    entries, column-major in the MatrixMarket file the readMM.py pipeline
    consumed (``aux/readMM.py:16-73``).  As with the shipped gr_30_30
    reconstruction, this environment has no network access, so values are
    canonical (1.0) and the row is labeled a reconstruction.  Built
    in memory rather than via a .mtx detour — a 4M-line text file would
    add ~60 MB and a multi-second parse for zero extra information.

    The default iteration count is the suite table's published N=10 for
    dense2 (``paper/Final_Report_DongBang_Tsai.tex:236-251``), so the
    real row is directly comparable to the suite-shaped synthetic row.
    """
    vals = np.ones(2000 * 2000, dtype=np.float32)
    return _problem_from_values(vals, nr=2000, iters=iters, seed=seed)


def real_instance_specs():
    """Shipped/reconstructed *real* suite instances: a list of
    ``(name, source_label, problem_factory)``.

    The benchmark suite is defined over named SuiteSparse matrices; these
    are the ones whose published definitions pin them down well enough to
    rebuild offline (pattern exact, values canonical, labels say so).
    The rest of the 15-instance suite stays honestly synthetic.
    """
    import os

    specs = []
    mtx = gr_30_30_path()
    if os.path.exists(mtx):
        specs.append(("gr_30_30", "real (HB/gr_30_30, reconstructed)",
                      lambda: problem_from_mtx(mtx, iters=50, seed=0)))
    specs.append(("dense2", "real (Williams/dense2, reconstructed)",
                  lambda: dense2_problem(iters=10, seed=0)))
    return specs


def problem_from_mtx(path: str, iters: int | None = None,
                     seed: int = 0) -> Problem:
    """readMM.py construction: values → ``a``; random sorted row-index subset
    → ``s``; random ``k``; uniform(−1,1) ``x``; N ∈ [5,100]."""
    _, _, vals, (nr, _) = read_matrix_market(path)
    return _problem_from_values(vals, nr=nr, iters=iters, seed=seed)


def _problem_from_values(vals: np.ndarray, nr: int,
                         iters: int | None = None, seed: int = 0) -> Problem:
    rng = np.random.default_rng(seed)
    n = vals.shape[0]
    p_interior = min(max(nr - 1, 1), n - 1)
    interior = np.sort(rng.choice(np.arange(1, n), size=p_interior,
                                  replace=False))
    s = np.concatenate([[0], interior, [n]]).astype(np.int32)
    q = max(nr, 2)
    k = rng.integers(0, q, size=n, dtype=np.int32)
    x = rng.uniform(-1, 1, size=q).astype(np.float32)
    if iters is None:
        iters = int(rng.integers(5, 101))
    prob = Problem(vals.astype(np.float32), s, k, x, iters)
    prob.validate()
    return prob
