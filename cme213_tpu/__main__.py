"""Top-level CLI: ``python -m cme213_tpu <workload> [args...]``.

One entry point over the six workload drivers (the reference shipped six
separate binaries; the registry in ``models.py`` is the single place they
are enumerated)."""

import sys

from .models import dispatch

if __name__ == "__main__":
    sys.exit(dispatch(sys.argv[1:]))
