"""Gang supervision: heartbeat plumbing, stall detection, and the full
supervised-launcher ladder — a rank killed mid-solve (deterministic
``rankkill`` injection) or frozen mid-"collective" (simulated hang) is
detected, the WHOLE gang is killed and relaunched, and the workload
resumes from the last committed epoch with a bitwise-clean final grid.

The end-to-end runs use a 1-process gang over 2 fake CPU devices — real
halo-exchange collectives inside the rank, real process death, real
launcher supervision — because this jaxlib has no multiprocess CPU
collectives (the capability the gated tests in test_multihost.py probe);
the supervision/commit protocol is identical at np>1.
"""

import os
import sys
import textwrap
import time

import numpy as np
import pytest

from cme213_tpu.core import faults, trace
from cme213_tpu.core.resilience import VirtualClock
from cme213_tpu.dist.supervisor import (GangSupervisor, HeartbeatWriter,
                                        heartbeat_from_env, read_heartbeat)


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.clear_events()
    yield
    faults.reset()


# ------------------------------------------------------------ heartbeats

def test_heartbeat_roundtrip(tmp_path):
    hb = HeartbeatWriter(str(tmp_path), rank=3)
    hb.beat(7)
    rec = read_heartbeat(str(tmp_path), 3)
    assert rec["rank"] == 3 and rec["step"] == 7
    assert rec["pid"] == os.getpid() and rec["incarnation"] == 0


def test_heartbeat_step_change_always_publishes(tmp_path):
    hb = HeartbeatWriter(str(tmp_path), rank=0, interval=3600)
    hb.beat(1)
    hb.beat(2)  # interval must not suppress a step CHANGE
    assert read_heartbeat(str(tmp_path), 0)["step"] == 2


def test_heartbeat_same_step_throttled(tmp_path):
    hb = HeartbeatWriter(str(tmp_path), rank=0, interval=3600)
    hb.beat(1)
    t0 = os.path.getmtime(hb.path)
    rec0 = read_heartbeat(str(tmp_path), 0)
    hb.beat(1)  # same step inside the interval: no rewrite
    assert os.path.getmtime(hb.path) == t0
    assert read_heartbeat(str(tmp_path), 0) == rec0


def test_heartbeat_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("CME213_HEARTBEAT_DIR", raising=False)
    assert heartbeat_from_env() is None
    monkeypatch.setenv("CME213_HEARTBEAT_DIR", str(tmp_path))
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    monkeypatch.setenv("CME213_HEARTBEAT_INTERVAL", "0.5")
    hb = heartbeat_from_env()
    hb.beat(4)
    assert read_heartbeat(str(tmp_path), 2)["step"] == 4
    assert hb.interval == 0.5


def test_missing_heartbeat_reads_none(tmp_path):
    assert read_heartbeat(str(tmp_path), 9) is None


# ------------------------------------------------------- stall detection

def test_supervisor_distinguishes_progress_from_frozen(tmp_path):
    clock = VirtualClock()
    sup = GangSupervisor(str(tmp_path), num_ranks=2, stall_timeout=0.15,
                         clock=clock)
    hb0 = HeartbeatWriter(str(tmp_path), 0)
    hb1 = HeartbeatWriter(str(tmp_path), 1)
    hb0.beat(1)
    hb1.beat(1)
    assert sup.stalled() == []          # first beats: progress
    clock.advance(0.2)
    hb0.beat(2)                         # rank 0 advances; rank 1 frozen
    stalled = sup.stalled()
    assert [s["rank"] for s in stalled] == [1]
    assert stalled[0]["step"] == 1 and stalled[0]["stalled_s"] >= 0.15


def test_supervisor_catches_rank_that_never_beat(tmp_path):
    """A rank wedged before its first beat (hung coordinator handshake) is
    timed from gang spawn."""
    clock = VirtualClock()
    sup = GangSupervisor(str(tmp_path), num_ranks=1, stall_timeout=0.1,
                         clock=clock)
    assert sup.stalled() == []
    clock.advance(0.15)
    assert [s["rank"] for s in sup.stalled()] == [0]


def test_supervisor_reset_clears_stale_beats(tmp_path):
    sup = GangSupervisor(str(tmp_path), num_ranks=1, stall_timeout=0.1)
    HeartbeatWriter(str(tmp_path), 0).beat(5)
    assert sup.step_of(0) == 5
    sup.reset()
    assert sup.step_of(0) is None       # previous incarnation's beat gone
    assert sup.stalled() == []          # and the progress clock restarted


# ------------------------------------------------- supervised launcher

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The supervised heat worker: a full distributed solve on 2 fake devices,
# epoch commits + heartbeats from the launcher env, final grid dumped
# full-precision for the bitwise check.
_HEAT_WORKER = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    from cme213_tpu.config import SimParams
    from cme213_tpu.apps.heat2d import run_distributed_supervised

    params = SimParams(nx=32, ny=32, order=4, iters=8)
    out = run_distributed_supervised(params)
    np.save({out_npy!r}, out)
""")

# A rank that heartbeats through step 1 then freezes forever in its first
# incarnation — the hung-collective signature (step counter stops while
# the process stays alive); the relaunched incarnation completes.
_STALL_WORKER = textwrap.dedent("""
    import sys, time
    sys.path.insert(0, {repo!r})
    from cme213_tpu.core.faults import incarnation
    from cme213_tpu.dist.supervisor import heartbeat_from_env

    hb = heartbeat_from_env()
    hb.beat(1)
    if incarnation() == 0:
        time.sleep(600)   # frozen: alive, but the step never advances
    hb.beat(2)
    print("recovered incarnation", incarnation())
""")


def _write_worker(tmp_path, src, **fmt):
    script = tmp_path / "worker.py"
    script.write_text(src.format(repo=_REPO, **fmt))
    return str(script)


def test_gang_rank_kill_restarts_and_recovers_bitwise(tmp_path, monkeypatch,
                                                      capsys):
    """The acceptance ladder: rankkill fires at epoch 1 (one commit
    banked), the launcher sees the rank die, condemns and relaunches the
    gang, the workload elastically resumes from the committed epoch, and
    the final grid is bitwise-equal to an uninterrupted sync-path run."""
    from cme213_tpu.config import SimParams
    from cme213_tpu.dist import make_mesh_1d, run_distributed_heat
    from cme213_tpu.dist.launch import launch_supervised

    out_npy = str(tmp_path / "final.npy")
    worker = _write_worker(tmp_path, _HEAT_WORKER, out_npy=out_npy)
    monkeypatch.setenv("CME213_FAULTS", "rankkill:0:1")
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    rc = launch_supervised(
        1, [sys.executable, worker], devices_per_proc=2,
        stall_timeout=120, max_restarts=1,
        ckpt_dir=str(tmp_path / "ckpt"), ckpt_every=2, timeout=300)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "injected kill: rank 0" in out
    assert "condemning the gang" in out
    assert "gang restart (incarnation 1/1)" in out

    params = SimParams(nx=32, ny=32, order=4, iters=8)
    ref = run_distributed_heat(params, make_mesh_1d(2))
    np.testing.assert_array_equal(np.load(out_npy), ref)
    assert trace.events("rank-failed")[-1]["reason"] == "exit"
    assert trace.events("gang-restart")[-1]["incarnation"] == 1


def test_gang_stall_detected_and_restarted(tmp_path, capsys):
    """A rank alive but frozen (step counter stuck) is condemned by
    --stall-timeout — not by the whole-job --timeout — and the relaunched
    incarnation completes."""
    from cme213_tpu.dist.launch import launch_supervised

    worker = _write_worker(tmp_path, _STALL_WORKER)
    t0 = time.monotonic()
    rc = launch_supervised(1, [sys.executable, worker],
                           stall_timeout=1.0, max_restarts=1, timeout=120)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert time.monotonic() - t0 < 60  # stall clock, not the job deadline
    assert "stalled at step 1" in out
    assert "recovered incarnation 1" in out
    assert trace.events("rank-failed")[-1]["reason"] == "stall"
    assert trace.events("gang-restart")


def test_gang_restart_budget_exhausted_fails(tmp_path, monkeypatch):
    from cme213_tpu.dist.launch import launch_supervised

    script = tmp_path / "die.py"
    script.write_text(
        f"import sys; sys.path.insert(0, {_REPO!r})\n"
        "from cme213_tpu.core import faults\n"
        "faults.maybe_kill_rank(step=0)\n")
    monkeypatch.setenv("CME213_FAULTS", "rankkill:0:0")
    rc = launch_supervised(1, [sys.executable, str(script)],
                           max_restarts=0, stall_timeout=60, timeout=60)
    assert rc == faults.KILL_EXIT


def test_gang_clean_exit_is_zero(tmp_path):
    from cme213_tpu.dist.launch import launch_supervised

    script = tmp_path / "ok.py"
    script.write_text("print('fine')\n")
    rc = launch_supervised(2, [sys.executable, str(script)],
                           stall_timeout=60, timeout=60)
    assert rc == 0


def test_launcher_cli_supervised_flags(tmp_path, capsys):
    """--stall-timeout routes main() into supervised mode, and the ckpt
    plumbing env reaches the ranks."""
    from cme213_tpu.dist.launch import main

    script = tmp_path / "env.py"
    script.write_text(
        "import os\n"
        "print('CKPT', os.environ['CME213_CKPT_DIR'],\n"
        "      os.environ['CME213_CKPT_EVERY'],\n"
        "      os.environ['CME213_RESUME'],\n"
        "      'HB' in os.environ['CME213_HEARTBEAT_DIR'] or\n"
        "      os.environ['CME213_HEARTBEAT_DIR'])\n")
    rc = main(["--np", "1", "--stall-timeout", "30",
               "--ckpt-dir", str(tmp_path / "c"), "--ckpt-every", "5",
               "--heartbeat-interval", "0.5", "--timeout", "60", "--",
               sys.executable, str(script)])
    out = capsys.readouterr().out
    assert rc == 0
    assert f"CKPT {tmp_path / 'c'} 5 0" in out
