"""Pin the capture retry-classification semantics (scripts/capture_lib.sh).

These shell predicates decide what device evidence is final vs re-run on
the next tunnel window — the logic has been the round's main source of
review findings, so the truth table lives in tests.
"""

import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "scripts", "capture_lib.sh")

GOOD_BENCH = ('{"metric": "heat2d ...", "value": 123.4, "unit": "GB/s", '
              '"kernels": [{"kernel": "xla", "ok": true}]}\n')
PARTIAL_BENCH = ('{"metric": "heat2d ...", "value": 14.6, "unit": "GB/s", '
                 '"kernels": [{"kernel": "xla", "ok": true}, '
                 '{"kernel": "pipeline-k8", "ok": false, '
                 '"error": "preflight: device unreachable"}]}\n')
# a dead-window bench output echoes the COMMITTED banked_device_rows
# (ok:true by construction) — promote_bench must not count those as
# live-measured rows, or a dead re-run could replace real evidence
DEAD_BENCH = ('{"metric": "heat2d ... (DEVICE UNAVAILABLE)", "value": 0.0, '
              '"unit": "GB/s", "vs_baseline": 0.0, "kernels": ['
              '{"kernel": "xla", "ok": false, '
              '"error": "preflight: device unreachable"}], '
              '"banked_device_rows": ['
              '{"kernel": "xla", "ok": true, "gbs": 50.85}, '
              '{"kernel": "pipeline-k4", "ok": true, "gbs": 251.8}]}\n')


def _call(fn: str, *args: str) -> int:
    return subprocess.run(
        ["bash", "-c", f'. "{LIB}"; {fn} "$@"', "_", *args],
        capture_output=True).returncode


@pytest.mark.parametrize("content,ok,complete", [
    (GOOD_BENCH, 0, 0),
    (PARTIAL_BENCH, 0, 1),   # usable headline, but NOT final evidence
    (DEAD_BENCH, 1, 1),
    ("", 1, 1),
])
def test_bench_predicates(tmp_path, content, ok, complete):
    f = tmp_path / "bench.json"
    f.write_text(content)
    assert _call("bench_ok", str(f)) == ok
    assert _call("bench_complete", str(f)) == complete


def test_bench_predicates_missing_file(tmp_path):
    missing = str(tmp_path / "nope.json")
    assert _call("bench_ok", missing) == 1
    assert _call("bench_complete", missing) == 1


def test_sweep_attempted_truth_table(tmp_path):
    out = tmp_path
    # captured CSV -> attempted
    (out / "a.csv").write_text("x\n1\n")
    assert _call("sweep_attempted", str(out), "a") == 0
    # no csv, sticky failure record -> attempted (not retried)
    (out / "b.failed").write_text("TypeError: bad tile\n")
    assert _call("sweep_attempted", str(out), "b") == 0
    # no csv, device failure record -> NOT attempted (retried next window)
    for tag in ("UNAVAILABLE: socket closed",
                "timeout after 2700s — device hang suspected",
                "preflight: device unreachable",
                "JaxRuntimeError: ... TPU device error ..."):
        (out / "c.failed").write_text(tag + "\n")
        assert _call("sweep_attempted", str(out), "c") == 1, tag
    # nothing recorded -> not attempted
    assert _call("sweep_attempted", str(out), "d") == 1


def test_row_predicates_truth_table(tmp_path):
    """tranche-1 per-kernel rows: ok = real number; conclusive = real
    number OR sticky failure (device-tagged failures are retried)."""
    cases = [
        ('{"kernel": "xla", "ok": true, "gbs": 123.4}', 0, 0),
        # sticky compile bug: evidence, not retried
        ('{"kernel": "pipeline-k4", "ok": false, '
         '"error": "TypeError: bad tile"}', 1, 0),
        # device-tagged failures: retried next window
        ('{"kernel": "xla", "ok": false, '
         '"error": "preflight: device unreachable"}', 1, 1),
        ('{"kernel": "xla", "ok": false, '
         '"error": "UNAVAILABLE: socket closed"}', 1, 1),
        ("", 1, 1),
    ]
    for content, ok, conclusive in cases:
        f = tmp_path / "row.json"
        f.write_text(content)
        assert _call("row_ok", str(f)) == ok, content
        assert _call("row_conclusive", str(f)) == conclusive, content
    missing = str(tmp_path / "nope.json")
    assert _call("row_ok", missing) == 1
    assert _call("row_conclusive", missing) == 1


def _signature(log_text: str, tmp_path) -> str:
    f = tmp_path / "sweep.stderr.log"
    f.write_text(log_text)
    out = subprocess.run(
        ["bash", "-c", f'. "{LIB}"; failure_signature "$1"', "_", str(f)],
        capture_output=True, text=True)
    return out.stdout


def test_failure_signature_anchors_to_final_failure(tmp_path):
    """A recovered-UNAVAILABLE warning that merely sits near the end of a
    long sticky-failure log must NOT produce a device signature; a device
    error inside the final traceback (or final lines) must."""
    sticky_tail = "\n".join(f"frame {i}" for i in range(20))
    # transient warning 10 lines from the end, then a sticky TypeError
    # traceback: the old 60-line window classified this as a device failure
    log = ("working...\nUNAVAILABLE: transient, recovered\n"
           + "\n".join(f"progress {i}" for i in range(8))
           + "\nTraceback (most recent call last):\n" + sticky_tail
           + "\nTypeError: unsupported tile\n")
    assert _signature(log, tmp_path) == ""
    # device error in the final traceback: signature found even when the
    # traceback is longer than any fixed tail window
    log = ("noise\n" * 30 + "Traceback (most recent call last):\n"
           + sticky_tail + "\njaxlib.JaxRuntimeError: UNAVAILABLE: dead\n")
    assert "UNAVAILABLE" in _signature(log, tmp_path)
    # no traceback at all: the run_all FAILED line within the last 15
    # lines carries the tag
    log = ("noise\n" * 30
           + "spmv_suite.csv: FAILED (RuntimeError: DEADLINE exceeded)\n")
    assert "DEADLINE" in _signature(log, tmp_path)
    # ...but an early transient warning with a sticky final line does not
    log = ("UNAVAILABLE: transient, recovered\n" + "noise\n" * 30
           + "heat_kernels.csv: FAILED (ValueError: bad order)\n")
    assert _signature(log, tmp_path) == ""


def test_python_device_tags_subset_of_shell_classifier():
    """_raise_if_device_error's tag set must stay a subset of DEVICE_ERR,
    or a sweep aborted for a device reason would be classified sticky."""
    import re

    from cme213_tpu.bench.sweeps import _raise_if_device_error

    src = open(LIB).read()
    pattern = re.search(r"DEVICE_ERR='([^']+)'", src).group(1)
    for tag in ("UNAVAILABLE", "DEADLINE", "unreachable", "device error"):
        try:
            _raise_if_device_error(RuntimeError(f"xx {tag} yy"))
        except RuntimeError:
            pass
        else:
            pytest.fail(f"python classifier no longer raises on {tag!r}")
        assert re.search(pattern, f"xx {tag} yy"), (
            f"shell DEVICE_ERR does not match python tag {tag!r}")


@pytest.mark.parametrize("platform,req,expect_rc", [
    ("cpu", "", 0),       # a platform that answers -> gate passes
    ("bogus9", "", 1),    # a platform that can't init -> gate fails closed
    ("cpu", "tpu", 1),    # answers, but is not the required platform
    ("cpu", "cpu", 0),    # answers and matches the required platform
])
def test_device_up_quick_gate(platform, req, expect_rc):
    """The pre-sweep gate (device_up_quick) passes iff a trivial device
    op completes (and the device matches the optional required platform)
    — a dead backend must fail in ~CAPTURE_PREFLIGHT_S seconds, not hang
    until the sweep's own multi-hour timeout."""
    env = {**os.environ, "JAX_PLATFORMS": platform,
           "CAPTURE_PREFLIGHT_S": "10"}
    rc = subprocess.run(
        ["bash", "-c", f'. "{LIB}"; device_up_quick "$1"', "_", req],
        capture_output=True, env=env, timeout=90, cwd=REPO).returncode
    assert rc == expect_rc


CAPTURE = os.path.join(REPO, "scripts", "tpu_capture.sh")


@pytest.mark.parametrize("old,new,expect", [
    (PARTIAL_BENCH, GOOD_BENCH, "new"),    # more rows -> promote
    (GOOD_BENCH, GOOD_BENCH, "new"),       # tie -> fresher wins
    (PARTIAL_BENCH, DEAD_BENCH, "old"),    # regression -> keep banked rows
    ("", DEAD_BENCH, "new"),               # nothing either way -> freshest
    (None, GOOD_BENCH, "new"),             # first capture ever
])
def test_promote_bench(tmp_path, old, new, expect):
    """A bench re-run must never replace a file holding more measured
    device rows than the new attempt banked (a window dying before the
    first kernel would otherwise erase earlier evidence)."""
    f = tmp_path / "bench.json"
    if old is not None:
        f.write_text(old)
    (tmp_path / "bench.json.new").write_text(new)
    # extract promote_bench from the capture script and drive it directly
    rc = subprocess.run(
        ["bash", "-c",
         f'. "{LIB}"; eval "$(sed -n \'/^promote_bench()/,/^}}/p\' '
         f'"{CAPTURE}")"; promote_bench "$1"', "_", str(f)],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert rc.returncode == 0, rc.stderr
    assert not (tmp_path / "bench.json.new").exists()
    assert f.read_text() == (new if expect == "new" else old)
