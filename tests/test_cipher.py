import numpy as np
import pytest

from cme213_tpu.ops import shift_cipher, shift_cipher_packed
from cme213_tpu.verify import check_exact, golden


@pytest.fixture
def text():
    rng = np.random.default_rng(0)
    # ASCII-ish corpus (printable range) like the reference's book text
    return rng.integers(32, 127, size=1 << 16, dtype=np.uint8)


def test_shift_matches_host_golden(text):
    import jax.numpy as jnp

    shift = 17
    ref = golden.host_shift_cipher(text, shift)
    out = np.asarray(shift_cipher(jnp.asarray(text), shift))
    res = check_exact(ref, out, "cipher u8")
    assert res, res.message


@pytest.mark.parametrize("width", [4, 8])
def test_packed_variants_match(text, width):
    import jax.numpy as jnp

    shift = 13  # no per-byte carry for printable ASCII + 13 < 256... (127+13)
    ref = golden.host_shift_cipher(text, shift)
    out = np.asarray(shift_cipher_packed(jnp.asarray(text), shift, width=width))
    res = check_exact(ref, out, f"cipher packed{width}")
    assert res, res.message


def test_wrapping_semantics():
    import jax.numpy as jnp

    data = np.array([250, 251, 255, 0], dtype=np.uint8)
    out = np.asarray(shift_cipher(jnp.asarray(data), 10))
    assert (out == golden.host_shift_cipher(data, 10)).all()
    assert out[2] == 9  # 255 + 10 wraps


def test_encrypt_decrypt_roundtrip(text):
    import jax.numpy as jnp

    enc = shift_cipher(jnp.asarray(text), 42)
    dec = np.asarray(shift_cipher(enc, 256 - 42))
    assert (dec == text).all()
