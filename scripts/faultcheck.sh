#!/bin/bash
# Fault-injection smoke gate — the resilience layer exercised end-to-end
# under an injected-failure matrix (CPU backend, deterministic faults,
# no sleeps).  CI runs this next to tier1.sh; humans run it the same way:
#
#   bash scripts/faultcheck.sh
#
# Asserts, per ISSUE 2:
#  1. bench harness: run_all under an injected first-attempt sweep failure
#     exits 0 (the retry recovers) with a POPULATED failures.json — a
#     single flaky sweep must not zero a capture run;
#  2. kernel ladder: spmv_scan under an injected pallas-fused failure
#     completes on a demoted rung with f64-checked-correct results, and
#     the demotion appears in the structured trace log;
#  3. launcher: an injected rank kill is survived by --max-restarts 1
#     (same rank id relaunched), and kills the job without the budget.
# And per ISSUE 3 (gang supervision + epoch commits):
#  4. supervised gang (1 process, 2 fake devices): an injected mid-solve
#     rank kill triggers a GANG restart that resumes from the last
#     committed epoch and completes;
#  5. supervised gang across 2 REAL ranks: same recovery with the halo
#     exchange riding cross-process collectives — auto-SKIPPED (not
#     failed) where this jaxlib can't do multiprocess CPU, using the
#     same capability probe as tests/test_multihost.py.
# And per ISSUE 4 (telemetry): the gang runs of steps 4/5 sink per-rank
# trace files (CME213_TRACE_FILE={rank}-templated), and
#  6. `trace summary`/`timeline`/`merge --timeline` over those files must
#     parse cleanly and contain the required commit spans + the full
#     recovery arc (rankkill -> verdict -> restart -> resume).
# And per ISSUE 5 (guarded execution):
#  7. conformance gate: an injected wrong-answer probe (`wrong:`) demotes
#     the poisoned rung, the served result is f64-checked correct, and
#     the trace CLI finds the conformance-failed event (--require gate);
#  8. admission control: an injected RESOURCE_EXHAUSTED (`oom:`) makes
#     the checkpointed heat solve shrink its chunk, retry, and complete
#     bitwise-equal to an un-faulted run, with the chunk-shrunk event in
#     the trace.
# And per ISSUE 8 (serving):
#  9. serving front end: an open-loop burst over a tiny bounded queue
#     sheds the excess with structured queue-shed results (429 analog,
#     accounting exact), and a fail:-poisoned kernel rung opens its
#     circuit breaker while the fallback rung keeps serving — both
#     verified from the SLO report AND via `trace summary --require`.
# And per ISSUE 10 (observability):
# 10. flight recorder: a serve run that dies on an unhandled exception
#     after serving traffic leaves a parseable flight-*.json black box
#     (reason, traceback, pre-crash events, metrics at death) that
#     `trace flight` renders.
# And per ISSUE 17 (chaos campaigns):
# 11. one in-process chaos campaign: a seeded multi-clause fault
#     cocktail armed against a live serving run, every global invariant
#     (zero loss, bitwise conformance, SLO report, one trace id, no
#     leaks) green — and the drawer is seed-deterministic (two draws of
#     the same seed are byte-identical).  The full game day (8 fleet
#     campaigns + fixture replay) is the tier1.yml chaos gate.
# On ANY failing step the merged gang timeline is printed for
# debuggability before the workspace is cleaned up.
set -euo pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu
OUT=$(mktemp -d)
on_exit() {
  rc=$?
  # a failing step leaves with rc != 0 (set -e): print the merged gang
  # timeline for debuggability before the workspace goes away.  (EXIT,
  # not ERR: steps probing expected failures under `set +e` must not
  # trigger it.)
  if [ "$rc" -ne 0 ] && ls "$OUT"/trace*.jsonl >/dev/null 2>&1; then
    echo "== faultcheck FAILED (rc=$rc); merged gang trace timeline:" >&2
    python -m cme213_tpu trace merge --timeline "$OUT"/trace*.jsonl >&2 || true
  fi
  rm -rf "$OUT"
}
trap on_exit EXIT

echo "== 1/10 run_all: injected sweep failure -> retry + failures.json"
CME213_FAULTS="fail:sweep.scan_bandwidth" \
    python -m cme213_tpu.bench.run_all --quick --out "$OUT" \
    --only scan_bandwidth
python - "$OUT" <<'PY'
import json, sys
m = json.load(open(sys.argv[1] + "/failures.json"))
assert m["failed"] == [], m
assert [r["sweep"] for r in m["retried"]] == ["scan_bandwidth"], m
print("failures.json populated:", m["retried"][0]["error"])
PY

echo "== 2/10 spmv ladder: injected pallas failure -> demoted, correct"
CME213_FAULTS="fail:spmv_scan.pallas-fused" python - <<'PY'
from cme213_tpu.apps import spmv_scan as sp
from cme213_tpu.core import trace
prob = sp.generate_problem(4096, 64, 63, iters=4, seed=0)
out = sp.run_spmv_scan(prob, kernel="pallas-fused")
served = trace.events("served")[-1]
assert served["demoted"] and served["rung"] == "blocked", served
errs = sp.external_check(prob, out)
assert errs["rel_l2"] < 1e-4, errs
print("demoted to", served["rung"], "rel_l2", errs["rel_l2"])
PY

echo "== 3/10 launcher: injected rank kill survived by --max-restarts 1"
CME213_FAULTS="rankkill:1:0" python -m cme213_tpu.dist.launch \
    --np 2 --max-restarts 1 --timeout 120 -- \
    python -c "import os; from cme213_tpu.core import faults; \
faults.maybe_kill_rank(); print('rank', os.environ['JAX_PROCESS_ID'], 'ok')"
if CME213_FAULTS="rankkill:1:0" python -m cme213_tpu.dist.launch \
    --np 2 --timeout 120 -- \
    python -c "from cme213_tpu.core import faults; faults.maybe_kill_rank()" \
    2>/dev/null; then
  echo "ERROR: rank kill without restart budget should fail the job" >&2
  exit 1
fi

cat > "$OUT/params_gang.in" <<'EOF'
32 32
1.0 1.0
0.4
8
4
5.0
1
1
100.0 25.0 0.0 50.0
EOF

echo "== 4/10 supervised gang: rankkill -> gang restart + epoch-commit resume"
# 1 process x 2 fake devices: real halo-exchange collectives in the rank,
# real process death, real gang supervision — works on every backend.
# Per-rank trace sinks feed step 6's CLI gate.
CME213_FAULTS="rankkill:0:1" JAX_PLATFORMS= \
CME213_TRACE_FILE="$OUT/trace4-{rank}.jsonl" python -m cme213_tpu.dist.launch \
    --np 1 --devices-per-proc 2 --stall-timeout 120 --max-restarts 1 \
    --ckpt-dir "$OUT/gang1" --ckpt-every 2 --timeout 300 -- \
    python -m cme213_tpu.apps.heat2d "$OUT/params_gang.in" --supervised \
    | tee "$OUT/gang1.log"
grep -q "gang restart (incarnation 1/1)" "$OUT/gang1.log"
grep -q "supervised solve complete" "$OUT/gang1.log"
test -f "$OUT/gang1/COMMIT"
# the full 8-iter solve finished: the final commit must carry step 8
python - "$OUT/gang1/COMMIT" <<'PY'
import json, sys
m = json.load(open(sys.argv[1]))
assert (m["step"], m["epoch"]) == (8, 4), m
print(f"gang recovery OK (final commit: epoch {m['epoch']}, "
      f"step {m['step']})")
PY

echo "== 5/10 supervised gang across 2 REAL ranks (capability-gated)"
set +e
CME213_FAULTS="rankkill:1:1" JAX_PLATFORMS= \
CME213_TRACE_FILE="$OUT/trace5-{rank}.jsonl" python -m cme213_tpu.dist.launch \
    --np 2 --devices-per-proc 1 --stall-timeout 120 --max-restarts 1 \
    --ckpt-dir "$OUT/gang2" --ckpt-every 2 --timeout 300 -- \
    python -m cme213_tpu.apps.heat2d "$OUT/params_gang.in" --supervised \
    > "$OUT/gang2.log" 2>&1
rc=$?
set -e
if python - "$OUT/gang2.log" <<'PY'
import sys
from cme213_tpu.dist.multihost import multiprocess_unsupported
sys.exit(0 if multiprocess_unsupported(open(sys.argv[1]).read()) else 1)
PY
then
  echo "SKIP: multiprocess CPU unsupported by this jaxlib (same capability" \
       "probe as tests/test_multihost.py)"
elif [ "$rc" != 0 ]; then
  echo "ERROR: 2-rank supervised gang failed for a non-capability reason" >&2
  tail -n 30 "$OUT/gang2.log" >&2
  exit 1
else
  grep -q "gang restart (incarnation 1/1)" "$OUT/gang2.log"
  grep -q "supervised solve complete" "$OUT/gang2.log"
  echo "2-rank gang recovery OK"
fi

echo "== 6/10 trace CLI over the per-rank gang traces (ISSUE 4)"
# step 4's files always exist; any unparseable line exits 2, a missing
# commit span or gang phase exits 1 — either fails the gate
python -m cme213_tpu trace summary "$OUT"/trace4-*.jsonl \
    --require "ckpt.commit,supervised distributed computation"
python -m cme213_tpu trace timeline "$OUT"/trace4-*.jsonl > /dev/null
python -m cme213_tpu trace merge --timeline "$OUT"/trace4-*.jsonl \
    > "$OUT/timeline4.txt"
# the reconstructed recovery arc: kill -> verdict -> restart -> resume
for marker in "fault-injected" "rank-failed" "gang-restart" \
              "commit-loaded" "gang-exit"; do
  grep -q "$marker" "$OUT/timeline4.txt"
done
echo "gang timeline reconstructed ($(wc -l < "$OUT/timeline4.txt") events)"
if ls "$OUT"/trace5-*.jsonl >/dev/null 2>&1; then
  # the 2-real-rank run (step 5) also left traces — merge must interleave
  # them even when the run itself was capability-skipped mid-flight
  python -m cme213_tpu trace merge --timeline "$OUT"/trace5-*.jsonl \
      > /dev/null
fi

echo "== 7/10 conformance gate: wrong: probe poison -> demotion (ISSUE 5)"
# the first conformance probe of spmv_scan (the requested pallas-fused
# rung) is perturbed; the gate must demote it, the next rung (blocked,
# probe call 2, clean) serves, and the result still passes the f64 check
CME213_FAULTS="wrong:spmv_scan:1" \
CME213_TRACE_FILE="$OUT/trace7.jsonl" python - <<'PY'
from cme213_tpu.apps import spmv_scan as sp
from cme213_tpu.core import trace
prob = sp.generate_problem(4096, 64, 63, iters=4, seed=0)
out = sp.run_spmv_scan(prob, kernel="pallas-fused")
served = trace.events("served")[-1]
assert served["demoted"] and served["rung"] == "blocked", served
failed = trace.events("rung-failed")[-1]
assert failed["kind"] == "wrong_answer", failed
assert trace.events("conformance-failed"), "no conformance-failed event"
errs = sp.external_check(prob, out)
assert errs["rel_l2"] < 1e-4, errs
print("wrong-answer rung demoted; served", served["rung"],
      "rel_l2", errs["rel_l2"])
PY
# the CLI gate the tier-1 workflow also runs: the event must be findable
python -m cme213_tpu trace summary "$OUT/trace7.jsonl" \
    --require conformance-failed
if python -m cme213_tpu trace summary "$OUT/trace7.jsonl" \
    --require no-such-event 2>/dev/null; then
  echo "ERROR: --require must fail on a missing event" >&2
  exit 1
fi

echo "== 8/10 admission: oom: -> chunk shrink, bitwise-equal completion"
CME213_FAULTS="oom:heat_chunk:1" \
CME213_TRACE_FILE="$OUT/trace8.jsonl" python - "$OUT" <<'PY'
import os
import sys
import numpy as np
from cme213_tpu.apps.heat2d import run_heat_checkpointed
from cme213_tpu.config import SimParams
from cme213_tpu.core import faults, trace
p = SimParams(nx=24, ny=24, order=2, iters=8)
out_f = run_heat_checkpointed(p, sys.argv[1] + "/oom_f.npz", every=4)
shrunk = trace.events("chunk-shrunk")
assert [(e["from_size"], e["to_size"]) for e in shrunk] == [(4, 2)], shrunk
del os.environ["CME213_FAULTS"]  # the reference run must be un-faulted
faults.reset()
out_c = run_heat_checkpointed(p, sys.argv[1] + "/oom_c.npz", every=4)
np.testing.assert_array_equal(out_f, out_c)
print("oom chunk shrink 4->2; result bitwise-equal to un-faulted run")
PY
python -m cme213_tpu trace summary "$OUT/trace8.jsonl" \
    --require chunk-shrunk

echo "== 9/10 serving: open-loop burst over a tiny queue sheds + breaker opens"
# 24 cipher requests burst at a 6-deep queue: backpressure MUST shed the
# excess with structured queue-shed events, and the fail:-poisoned packed
# rung MUST open its circuit (3 classified failures) while the bytes rung
# keeps serving — both findable by the --require gate.
CME213_FAULTS="fail:serve.cipher.packed:1:4" \
CME213_TRACE_FILE="$OUT/trace9.jsonl" \
  python -m cme213_tpu serve loadgen --mode open --burst 24 --requests 24 \
    --capacity 6 --max-batch 2 --mix cipher --breaker-threshold 3 \
    --json > "$OUT/slo9.json"
python - "$OUT/slo9.json" <<'PY'
import json
import sys
rep = json.load(open(sys.argv[1]))
assert rep["shed"] > 0, rep
assert rep["shed_by_reason"].get("queue-full", 0) == rep["shed"], rep
assert rep["served"] + rep["shed"] == rep["requests"], rep
assert rep["breaker"]["opened"] >= 1, rep
assert rep["demotions"] >= 3, rep
print(f"overload shed {rep['shed']}/{rep['requests']}, served "
      f"{rep['served']}, breaker opened {rep['breaker']['opened']}")
PY
python -m cme213_tpu trace summary "$OUT/trace9.jsonl" \
    --require queue-shed,breaker-open

echo "== 10/10 flight recorder: a crashing serve run leaves its black box"
# serve real traffic first (the dump must have a history worth reading),
# then die on an unhandled exception: the armed recorder writes the
# flight dump on the way down — reason, traceback, the pre-crash event
# ring, and the metrics registry at death, all in one parseable file
mkdir -p "$OUT/flight"
set +e
CME213_FLIGHT_DIR="$OUT/flight" python - > "$OUT/flight.log" 2>&1 <<'PY'
from cme213_tpu.core import flight
flight.install()
from cme213_tpu.serve import OK, Server
from cme213_tpu.serve.loadgen import build_mix, run_load
run = run_load(Server(max_batch=2), build_mix("cipher", 6, seed=0),
               mode="closed", concurrency=3)
assert all(r.status == OK for r in run["results"])
raise RuntimeError("injected serve crash after 6 served")
PY
rc=$?
set -e
if [ "$rc" -eq 0 ]; then
  echo "ERROR: crashing serve run exited 0" >&2
  exit 1
fi
grep -q "injected serve crash" "$OUT/flight.log"   # chained hook printed
DUMP=$(ls "$OUT"/flight/flight-*.json)
python - "$DUMP" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc.get("flight") == 1, sorted(doc)
assert doc["reason"] == "unhandled-exception", doc["reason"]
assert "injected serve crash" in doc["traceback"], doc["traceback"]
assert doc["metrics"]["counters"]["serve.batches"] >= 1, doc["metrics"]
assert any(e["event"] == "request-served" for e in doc["events"]), \
    "no pre-crash serve history in the dump"
print(f"flight dump OK: {len(doc['events'])} pre-crash events captured")
PY
# render to a file, not a pipe: `grep -q` closing the pipe early would
# kill the renderer with SIGPIPE under pipefail
python -m cme213_tpu trace flight "$DUMP" > "$OUT/flight-render.txt"
grep -q "reason 'unhandled-exception'" "$OUT/flight-render.txt"
grep -q "injected serve crash" "$OUT/flight-render.txt"

# 11. chaos campaign smoke: the drawer is seed-deterministic, and one
# in-process campaign (seeded cocktail armed against a live serving
# run) holds all five global invariants
python -m cme213_tpu chaos draw --seed 7 --campaigns 2 \
  --mix cipher,sort > "$OUT/draw-a.txt"
python -m cme213_tpu chaos draw --seed 7 --campaigns 2 \
  --mix cipher,sort > "$OUT/draw-b.txt"
cmp "$OUT/draw-a.txt" "$OUT/draw-b.txt"
python -m cme213_tpu chaos run --seed 7 --campaigns 1 \
  --mix cipher,sort --requests 10 --json > "$OUT/chaos.json"
python - "$OUT/chaos.json" <<'PY'
import json, sys
out = json.load(open(sys.argv[1]))
assert out["ok"] and out["violations_total"] == 0, out
c = out["campaigns"][0]
assert len(c["cocktail"].split(",")) >= 2, c["cocktail"]
assert c["report"]["served"] + c["report"]["shed"] == 10, c["report"]
print(f"chaos campaign OK: {c['cocktail']} held all invariants")
PY

echo "faultcheck OK"
