"""Segmented inclusive scan — the hw_final engine primitive.

TPU-native redesign of the reference's intra-warp segmented-scan kernel
(one 32-thread warp per segment sliding a 31-element Hillis-Steele window,
``hw/hw_final/programming/fp.cu:28-59``).  TPUs have no warps; the idiomatic
form is a flag-based associative scan (Blelloch/Sengupta operator, cf.
``my-refs/scan.pdf``): scan pairs ``(value, head_flag)`` with

    (va, fa) ⊕ (vb, fb) = (vb + (fb ? 0 : va), fa | fb)

which is associative, so ``lax.associative_scan`` runs it in log depth fused
by XLA across the whole array regardless of segment boundaries — replacing
the reference's data-dependent per-segment loops with regular control flow.

Two XLA forms live here, behind the size-dispatching ``segmented_scan``:
the flat log-sweep (``segmented_scan_flat``, O(n·log n) work, bitwise-
stable) and the blocked Blelloch/Sengupta 3-phase decomposition
(``segmented_scan_blocked``, O(n) work per pass — per-block local scans →
scan of block carries → broadcast-add, the same shape as
``ops/scan.py:blocked_inclusive_scan`` and the mesh-scale ``dist/scan.py``).

Segment descriptors match the reference's: ``s`` = sorted segment start
indices with ``s[0] == 0`` (validated like ``load()``,
``hw/hw_final/programming/aux/mp1-util.h:81-169``); the precomputed
``key[i] = segment id`` vector (``fp.cu:111-125``) is ``segment_ids`` here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def head_flags_from_starts(seg_starts: jnp.ndarray, n: int) -> jnp.ndarray:
    """int32 {0,1} vector with 1 at each segment head."""
    flags = jnp.zeros((n,), jnp.int32)
    return flags.at[seg_starts].set(1, mode="drop")


def segment_ids_from_starts(seg_starts: jnp.ndarray, n: int) -> jnp.ndarray:
    """``key[i] = segment id`` (the fp.cu:111-125 precompute): cumulative sum
    of head flags minus one."""
    return jnp.cumsum(head_flags_from_starts(seg_starts, n)) - 1


# Auto-dispatch threshold: below this the flat log-sweep (bitwise-stable,
# compile-cheap) runs; at/above it the blocked O(n) form wins — the flat
# sweep moves n·log2(n) elements through HBM per scan while the blocked
# form moves ~3n (local cumsum pass + tiny carry scan + broadcast-add).
# 2^16 sits well under the 1M crossover the bench sweep demonstrates while
# keeping every existing small-shape test on the bitwise flat path.  This
# is the DEFAULT: the auto dispatch consults the tuning cache first
# (``scan_threshold`` / ``core/tune.py``), so a measured crossover for
# this device overrides it and ``CME213_TUNE=0`` restores it.
BLOCKED_SCAN_THRESHOLD = 1 << 16
# Per-block extent of the blocked decomposition.  Large enough that the
# inter-block carry scan (n / BLOCK elements, still log-sweep) is noise,
# small enough that a block's running cumsum stays cache/VMEM resident.
DEFAULT_SCAN_BLOCK = 4096


def scan_threshold() -> int:
    """The flat/blocked crossover the auto dispatch uses: the measured
    winner for this device (``core/tune.py``, op ``segmented_scan``,
    shape class ``crossover``) when one is cached, else the built-in
    ``BLOCKED_SCAN_THRESHOLD``.  Read at trace time — array lengths are
    static under jit, so the consult costs nothing per element and each
    shape still compiles exactly one kernel."""
    from ..core import tune

    rec = tune.lookup("segmented_scan", "crossover")
    if rec is not None:
        try:
            return int(rec["statics"].get("threshold",
                                          BLOCKED_SCAN_THRESHOLD))
        except (TypeError, ValueError):
            pass  # malformed cache entry: the default must keep serving
    return BLOCKED_SCAN_THRESHOLD


def segmented_scan_flat(values: jnp.ndarray,
                        head_flags: jnp.ndarray) -> jnp.ndarray:
    """Inclusive segmented sum scan over (value, flag) pairs — flat form.

    Hillis-Steele log-depth sweep — the same doubling-stride recurrence the
    reference's ``scan_warp`` runs over a 31-element warp window
    (fp.cu:28-58), here applied to the whole array at once with the
    segment-aware operator: at stride d,

        v[i] += f[i] ? 0 : v[i-d]        (stop at segment heads)
        f[i] |= f[i-d]

    One traced body under ``fori_loop`` (stride computed from the loop index)
    keeps compilation O(1) in n.  O(n·log n) work/traffic — preferred only
    for small n (see ``segmented_scan`` for the dispatch).
    """
    n = values.shape[0]
    steps = max(1, (n - 1).bit_length())
    idx = jnp.arange(n, dtype=jnp.int32)

    def body(i, carry):
        v, f = carry
        d = jnp.int32(1) << i
        pv = jnp.roll(v, d)
        pf = jnp.roll(f, d)
        valid = idx >= d
        add = jnp.where(valid & (f == 0), pv, jnp.zeros_like(v))
        newf = jnp.where(valid, f | pf, f)
        return (v + add, newf)

    out, _ = lax.fori_loop(0, steps, body, (values, head_flags.astype(jnp.int32)))
    return out


def segmented_scan_blocked(values: jnp.ndarray, head_flags: jnp.ndarray,
                           block_size: int = DEFAULT_SCAN_BLOCK) -> jnp.ndarray:
    """Inclusive segmented sum scan — blocked O(n) form.

    The Blelloch/Sengupta 3-phase decomposition (``my-refs/scan.pdf``;
    SURVEY §2.7 P7/P8), mirroring ``blocked_inclusive_scan`` in
    ``ops/scan.py`` with the segment-aware carry:

    1. per-block LOCAL segmented scans, computed in O(block) as
       ``cumsum(v) − cumsum[last head at or before i − 1]`` (reset-by-
       subtraction — one cumsum pass plus one gather, no log sweep);
    2. a segmented scan of the per-block open-segment carries
       ``(last local value, block contains a head?)`` over the n/block
       block summaries (flat log-sweep: negligible at that length);
    3. broadcast-add of each block's incoming carry to its elements
       before the block's first head.

    Total work and HBM traffic are O(n) per pass, vs O(n·log n) for the
    flat sweep.  Association differs from the flat form, so float results
    agree to rounding, not ULP (the tolerance model documented in
    ``ops/segmented_pallas.py``); on integer-valued inputs the two are
    exact, hence bitwise-equal.

    Pads internally to a block multiple (pad isolated in its own segment
    and dropped on return).
    """
    n = values.shape[0]
    flags = head_flags.astype(jnp.int32)
    nblk = max(1, -(-n // block_size))
    padded = nblk * block_size
    if padded != n:
        v = jnp.zeros((padded,), values.dtype).at[:n].set(values)
        f = jnp.zeros((padded,), jnp.int32).at[:n].set(flags)
        f = f.at[n].set(1)  # quarantine the pad in its own segment
    else:
        v, f = values, flags
    v2 = v.reshape(nblk, block_size)
    f2 = f.reshape(nblk, block_size)

    # phase 1: local segmented scan per block, reset-by-subtraction
    cs = jnp.cumsum(v2, axis=1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (nblk, block_size), 1)
    # index of the last head at or before each position (−1: none yet)
    hp = lax.cummax(jnp.where(f2 > 0, lane, -1), axis=1)
    base = jnp.where(
        hp >= 1,
        jnp.take_along_axis(cs, jnp.maximum(hp - 1, 0), axis=1),
        jnp.zeros_like(cs))
    local = cs - base

    # phase 2: segmented scan of block carries; the local scan already
    # resets at heads, so the last element's value IS the running sum of
    # the block's open segment (same invariant as the Pallas kernel's
    # cross-tile carry and dist/scan.py's shard carry)
    carry_v = local[:, -1]
    carry_f = (hp[:, -1] >= 0).astype(jnp.int32)
    inc_v = segmented_scan_flat(carry_v, carry_f)
    # exclusive incoming carry for block b = inclusive through block b−1
    incoming = jnp.concatenate([jnp.zeros((1,), inc_v.dtype), inc_v[:-1]])

    # phase 3: add the carry to elements before each block's first head
    no_head_yet = hp < 0
    out = local + jnp.where(no_head_yet, incoming[:, None],
                            jnp.zeros_like(local))
    return out.reshape(padded)[:n]


def segmented_scan(values: jnp.ndarray, head_flags: jnp.ndarray, *,
                   block_size: int | None = None) -> jnp.ndarray:
    """Inclusive segmented sum scan — auto-dispatching entry point.

    Small arrays (n < ``scan_threshold()`` — tuned-or-default crossover)
    run the flat log-sweep (``segmented_scan_flat``, bitwise-stable with
    prior releases); larger arrays run the blocked O(n) form
    (``segmented_scan_blocked``), at ``block_size`` when the caller (or
    the tuner, via ``apps.spmv_scan``) pins one.  The length is static
    under jit, so the dispatch costs nothing at trace time and each
    shape compiles exactly one kernel.
    """
    if values.shape[0] >= scan_threshold():
        return segmented_scan_blocked(values, head_flags,
                                      block_size or DEFAULT_SCAN_BLOCK)
    return segmented_scan_flat(values, head_flags)


def segmented_scan_from_starts(values: jnp.ndarray, seg_starts: jnp.ndarray) -> jnp.ndarray:
    flags = head_flags_from_starts(seg_starts, values.shape[0])
    return segmented_scan(values, flags)


def segmented_scan_dense(values: jnp.ndarray, seg_starts: jnp.ndarray,
                         max_seg_len: int) -> jnp.ndarray:
    """Dense per-segment formulation — the regular-shape analog of the
    reference's naive one-thread-per-segment kernel (``fp_old.cu:30-58``).

    Scatters each segment into a row of a (p, max_seg_len) matrix, cumsums
    along the row axis, and gathers back.  O(p·max_seg_len) work — efficient
    only when segment lengths are balanced; kept as the performance
    strawman/alternative, exactly the role fp_old.cu played.
    """
    n = values.shape[0]
    ids = segment_ids_from_starts(seg_starts, n)
    offs = jnp.arange(n, dtype=jnp.int32) - seg_starts[ids]
    p = seg_starts.shape[0]
    dense = jnp.zeros((p, max_seg_len), values.dtype)
    dense = dense.at[ids, offs].set(values, mode="drop")
    scanned = jnp.cumsum(dense, axis=1)
    return scanned[ids, offs]


def validate_segments(seg_starts, n: int, num_segments: int | None = None) -> None:
    """Host-side invariant checks, as the reference ``load()`` asserts
    (aux/mp1-util.h:128-148): strictly increasing, s[0]==0, all < n."""
    import numpy as np

    s = np.asarray(seg_starts)
    if num_segments is not None and s.shape[0] != num_segments:
        raise ValueError(f"expected {num_segments} segments, got {s.shape[0]}")
    if s.shape[0] == 0 or s[0] != 0:
        raise ValueError("first segment must start at 0")
    if (np.diff(s) <= 0).any():
        raise ValueError("segment starts must be strictly increasing")
    if s[-1] >= n:
        raise ValueError("segment start beyond array end")
