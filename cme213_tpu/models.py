"""Workload registry — the framework's model-family index.

The framework's "model families" are the six reference workloads
(SURVEY §0 table); each registry entry names its driver module's CLI
entry point and the reference unit it rebuilds.  ``python -m cme213_tpu
<workload> [args...]`` dispatches through this table (see ``__main__.py``).
"""

from __future__ import annotations

import sys
from dataclasses import dataclass
from typing import Callable


@dataclass(frozen=True)
class Workload:
    name: str
    reference_unit: str
    summary: str
    run: Callable[[list[str]], int]


def _cipher(argv: list[str]) -> int:
    from .apps import cipher

    return cipher.main(["cipher", *argv])


def _pagerank(argv: list[str]) -> int:
    from .apps import pagerank

    known = ("num_nodes", "avg_edges", "iterations", "seed")
    kwargs = {}
    for a in argv:
        if not (a.startswith("--") and "=" in a):
            print(f"pagerank: unknown argument {a!r} "
                  f"(expected --key=value with key in {known})",
                  file=sys.stderr)
            return 2
        key, value = a[2:].split("=", 1)
        key = key.replace("-", "_")
        if key not in known:
            print(f"pagerank: unknown option --{key}", file=sys.stderr)
            return 2
        kwargs[key] = int(value)
    return 0 if pagerank.main(**kwargs) else 1


def _heat2d(argv: list[str]) -> int:
    from .apps import heat2d

    return heat2d.main(["heat2d", *argv])


def _vigenere(argv: list[str]) -> int:
    from .apps import vigenere

    return vigenere.main(["vigenere", *argv])


def _sorts(argv: list[str]) -> int:
    from .apps import sorts

    return sorts.main(["sorts", *argv])


def _spmv_scan(argv: list[str]) -> int:
    from .apps import spmv_scan

    return spmv_scan.main(["spmv_scan", *argv])


def _trace(argv: list[str]) -> int:
    from . import trace_cli

    return trace_cli.main(argv)


def _serve(argv: list[str]) -> int:
    from . import serve

    return serve.main(argv)


def _tune(argv: list[str]) -> int:
    from . import tune_cli

    return tune_cli.main(argv)


def _doctor(argv: list[str]) -> int:
    from . import doctor_cli

    return doctor_cli.main(argv)


def _collect(argv: list[str]) -> int:
    from .core import collector

    return collector.main(argv)


def _top(argv: list[str]) -> int:
    from . import top_cli

    return top_cli.main(argv)


def _numerics(argv: list[str]) -> int:
    from . import numerics_cli

    return numerics_cli.main(argv)


def _fleet(argv: list[str]) -> int:
    from . import fleet_cli

    return fleet_cli.main(argv)


def _chaos(argv: list[str]) -> int:
    from . import chaos_cli

    return chaos_cli.main(argv)


WORKLOADS: dict[str, Workload] = {
    w.name: w
    for w in (
        Workload("cipher", "hw1", "Caesar shift cipher (device bandwidth "
                 "ladder: 1/4/8-byte lanes)", _cipher),
        Workload("pagerank", "hw1", "CSR PageRank iteration vs host golden",
                 _pagerank),
        Workload("heat2d", "hw2/hw5", "2-D heat diffusion: XLA + Pallas "
                 "kernels, optional --distributed mesh run", _heat2d),
        Workload("vigenere", "hw3", "Vigenère create/crack via device "
                 "analytics pipelines", _vigenere),
        Workload("sorts", "hw4", "host OpenMP merge/radix sorts + "
                 "TPU-resident sort path", _sorts),
        Workload("spmv_scan", "hw_final", "iterated gather·multiply + "
                 "segmented scan engine", _spmv_scan),
        # not a reference workload: the offline analysis pass over the
        # telemetry sinks every workload above writes (SURVEY §5's
        # spreadsheet step, made a first-class tool)
        Workload("trace", "telemetry", "summary | timeline | merge | "
                 "export (Perfetto) | regress | metrics (Prometheus "
                 "text) | flight (crash dump) over CME213_TRACE_FILE "
                 "JSON-lines traces and bench artifacts", _trace),
        # not a reference workload: the multi-tenant front end serving
        # the workloads above as a request population (bounded queue,
        # shape-class batching, deadlines, breaker, degradation)
        Workload("serve", "serving", "loadgen: drive the bounded-queue "
                 "batching front end with synthetic load, print an SLO "
                 "report; warmup: pre-compile the canonical serving "
                 "buckets for warm starts", _serve),
        # not a reference workload: the offline search that replaces the
        # reference's hand-tuned constants (hw2 tile shapes, hw_final
        # warp-scan sizing) with measured winners dispatch consumes
        Workload("tune", "autotune", "run: conformance-gate and time each "
                 "op's registered candidate configs, persist winners to "
                 "CME213_TUNE_CACHE; show | clear the cached winners",
                 _tune),
        # not a reference workload: the diagnostic layer standing in for
        # the reference's checkCudaErrors/cudaGetLastError discipline —
        # staged device-health probes + predicted-vs-measured calibration
        Workload("doctor", "diagnostics", "staged device-health ladder "
                 "(enumerate, memory, timed liveness; exit 1 when "
                 "unhealthy, --json for the structured report); "
                 "calibrate: roofline cost models vs XLA cost_analysis "
                 "per (op, rung, shape_class)", _doctor),
        # not a reference workload: the LIVE half of the telemetry story
        # (the reference only had post-run timing tables, hw5) — tail
        # per-rank sinks into one merged fleet view while the gang runs
        Workload("collect", "telemetry", "tail per-rank trace sinks into "
                 "a live merged fleet view: one-shot state (--once/"
                 "--json) or a followed merged JSONL stream (--follow)",
                 _collect),
        Workload("top", "telemetry", "live fleet console over the "
                 "collector: per-rank state/step/heartbeat-age rows, "
                 "fleet gauges, recent events; deterministic --once/"
                 "--json for CI", _top),
        # not a reference workload: the numeric-health report over trace
        # sinks — shadow-sample drift, budget burns/demotions, sentinel
        # trips, solver convergence; exit codes are the CI gate
        Workload("numerics", "telemetry", "report: numeric-health rollup "
                 "over trace sinks (shadow drift samples, error-budget "
                 "burns and rung demotions, output-sentinel trips, "
                 "solver convergence/stall); --json for CI, "
                 "--max-over-budget/--forbid-stall gate with exit 1",
                 _numerics),
        # not a reference workload: the replicated serving tier — the
        # hw5 gang machinery (supervised relaunch, incarnations,
        # per-rank sinks) repurposed for N independent server replicas
        # behind a tenant-fair, SLO-burn-autoscaling front end
        Workload("fleet", "serving", "up: run a replicated serving fleet "
                 "(socket front end, tenant-fair router, per-replica "
                 "breakers, supervised relaunch with zero accepted-"
                 "request loss, SLO-burn autoscaling); worker: one "
                 "replica process (spawned by up)", _fleet),
        # not a reference workload: the game-day layer composing all of
        # the above — seeded fault cocktails armed against a live
        # serving run, global invariants checked after every campaign,
        # violations ddmin-shrunk to minimal replayable fixtures
        Workload("chaos", "robustness", "run: seeded chaos campaigns "
                 "(randomized fault cocktails from the CME213_FAULTS "
                 "grammar, matrix-filtered, armed against a live "
                 "inproc/fleet serving run; zero-loss + bitwise-"
                 "conformance + SLO-report + one-trace + no-leak "
                 "invariants; violations shrink to banked fixtures); "
                 "draw | replay | matrix", _chaos),
    )
}


def usage() -> str:
    lines = ["usage: python -m cme213_tpu <workload> [args...]", "",
             "workloads:"]
    for w in WORKLOADS.values():
        lines.append(f"  {w.name:<10} [{w.reference_unit}] {w.summary}")
    return "\n".join(lines)


def dispatch(argv: list[str]) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print(usage())
        return 0
    name = argv[0]
    w = WORKLOADS.get(name)
    if w is None:
        print(f"unknown workload {name!r}\n\n{usage()}", file=sys.stderr)
        return 2
    return w.run(argv[1:])
