"""In-process metrics registry — named counters, gauges, histograms.

The reference derived every metric offline, in spreadsheets over printed
timer lines (SURVEY §5); a production system pulls named metrics from the
process instead (the Prometheus model).  This registry is that pull
surface, deliberately tiny: no exposition server — just named
instruments a solver increments on its host path, a ``snapshot()`` the
bench harness (``bench/run_all.py``) and the trace sink (a
``metrics-snapshot`` event at exit) serialize, and a
``render_prometheus()`` text rendering (dotted-name suffixes folded
into labels for the known families) that ``write_exposition()`` dumps
atomically to ``CME213_METRICS_FILE`` for external scrapers::

    from cme213_tpu.core import metrics
    metrics.counter("fallback.demotions").inc()
    metrics.histogram("commit.ms").observe(12.3)
    metrics.gauge("gang.world").set(4)

Instruments are created on first use and process-global; snapshotting is
lock-consistent.  Histograms keep a bounded ring of recent observations
(``KEEP`` = 4096) for percentiles plus exact count/sum — a long solve
cannot grow memory without bound.  Everything here is host-side dict and
deque work: effectively free next to any device work it measures, and
exactly zero when never called.

``delta(before, after)`` diffs two snapshots (counter/histogram-count
deltas, latest gauge values) — what ``run_all`` attaches to each sweep's
row set in ``metrics.json``.
"""

from __future__ import annotations

import atexit
import math
import os
import re
import threading
from bisect import bisect_left
from collections import deque

#: optional path for a Prometheus text-format dump, written atomically at
#: interpreter exit (and periodically by long-running callers such as the
#: serving loop) so external scrapers read live state without parsing
#: trace JSONL
METRICS_FILE_ENV = "CME213_METRICS_FILE"

#: truthy -> render histograms in the pre-bucket quantile-summary form
#: (``{quantile="..."}`` lines) instead of native ``_bucket`` families
SUMMARY_COMPAT_ENV = "CME213_METRICS_SUMMARY_COMPAT"

#: observations retained per histogram for percentile estimates
KEEP = 4096

#: log-spaced cumulative-bucket upper bounds (powers of two from 0.25 to
#: 32768 — ms-scale latencies land mid-range), plus an implicit +Inf;
#: exact per-bucket counts are kept incrementally so the Prometheus
#: rendering needs no window replay and merges across ranks exactly
BUCKET_BOUNDS: tuple[float, ...] = tuple(
    float(2.0 ** e) for e in range(-2, 16))

_LOCK = threading.Lock()
_COUNTERS: dict[str, "Counter"] = {}
_GAUGES: dict[str, "Gauge"] = {}
_HISTOGRAMS: dict[str, "Histogram"] = {}


class Counter:
    """Monotonic named count (demotions, retries, commits, faults)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> "Counter":
        with _LOCK:
            self.value += n
        return self


class Gauge:
    """Last-write-wins named value (world size, live epoch, ...)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str) -> None:
        self.name = name
        self.value = None

    def set(self, value) -> "Gauge":
        with _LOCK:
            self.value = value
        return self


class Histogram:
    """Named distribution: exact count/sum/min/max plus percentiles over
    the last ``KEEP`` observations (a ring — bounded by construction)."""

    __slots__ = ("name", "count", "total", "min", "max", "_recent",
                 "buckets")

    def __init__(self, name: str) -> None:
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._recent: deque = deque(maxlen=KEEP)
        # per-bucket (non-cumulative) counts; index len(BUCKET_BOUNDS)
        # is the +Inf overflow bucket
        self.buckets: list[int] = [0] * (len(BUCKET_BOUNDS) + 1)

    def observe(self, value: float) -> "Histogram":
        value = float(value)
        with _LOCK:
            self.count += 1
            self.total += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            self._recent.append(value)
            self.buckets[bisect_left(BUCKET_BOUNDS, value)] += 1
        return self

    def percentile(self, q: float) -> float | None:
        """Nearest-rank percentile over the retained window.

        ``q`` is a fraction in [0, 1].  The result is the nearest-rank
        order statistic — ``sorted(window)[ceil(q * n) - 1]`` (clamped to
        the ends, so ``q=0`` is the window minimum and ``q=1`` the window
        maximum) — computed over the last ``KEEP`` observations only:
        once the ring has wrapped, older observations no longer influence
        percentiles (count/sum/min/max stay exact over the full stream).
        Returns None when no observations were retained.
        """
        with _LOCK:
            vals = sorted(self._recent)
        return _nearest_rank(vals, q)

    def _summary_locked(self) -> dict:
        vals = sorted(self._recent)

        def pct(q):
            return _nearest_rank(vals, q)

        return {
            "count": self.count,
            "sum": round(self.total, 6),
            "mean": round(self.total / self.count, 6) if self.count else None,
            "min": self.min,
            "max": self.max,
            "p50": pct(0.50),
            "p90": pct(0.90),
            "p99": pct(0.99),
            "buckets": list(self.buckets),
        }


def _nearest_rank(sorted_vals, q: float) -> float | None:
    """Nearest-rank order statistic of pre-sorted values; None if empty.

    Rank is ``ceil(q * n)`` (1-based), clamped into [1, n] so q=0 yields
    the minimum and q=1 the maximum of the given window.
    """
    n = len(sorted_vals)
    if not n:
        return None
    rank = math.ceil(q * n)
    return sorted_vals[min(n - 1, max(0, rank - 1))]


def counter(name: str) -> Counter:
    with _LOCK:
        c = _COUNTERS.get(name)
        if c is None:
            c = _COUNTERS[name] = Counter(name)
    return c


def gauge(name: str) -> Gauge:
    with _LOCK:
        g = _GAUGES.get(name)
        if g is None:
            g = _GAUGES[name] = Gauge(name)
    return g


def histogram(name: str) -> Histogram:
    with _LOCK:
        h = _HISTOGRAMS.get(name)
        if h is None:
            h = _HISTOGRAMS[name] = Histogram(name)
    return h


def snapshot() -> dict:
    """Lock-consistent ``{counters, gauges, histograms}`` view of the
    registry — JSON-serializable (what trace files and bench artifacts
    embed)."""
    with _LOCK:
        return {
            "counters": {k: c.value for k, c in sorted(_COUNTERS.items())},
            "gauges": {k: g.value for k, g in sorted(_GAUGES.items())},
            "histograms": {k: h._summary_locked()
                           for k, h in sorted(_HISTOGRAMS.items())},
        }


def delta(before: dict, after: dict) -> dict:
    """What changed between two snapshots: nonzero counter deltas, gauges
    at their ``after`` values, histograms that saw new observations (with
    their ``after`` percentiles — percentiles don't subtract)."""
    counters = {}
    for k, v in after.get("counters", {}).items():
        d = v - before.get("counters", {}).get(k, 0)
        if d:
            counters[k] = d
    histograms = {}
    for k, h in after.get("histograms", {}).items():
        d = h["count"] - before.get("histograms", {}).get(k, {}).get("count", 0)
        if d:
            histograms[k] = {**h, "count_delta": d}
    return {"counters": counters,
            "gauges": dict(after.get("gauges", {})),
            "histograms": histograms}


#: metric-name characters Prometheus allows; everything else becomes "_"
_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")

#: dotted-name families whose trailing segments are really label values;
#: (regex, family name, label names) — first match wins, everything else
#: renders as a flat sanitized name
_LABEL_FAMILIES = (
    (re.compile(r"^serve\.shed\.(?P<reason>.+)$"),
     "serve_shed_total", ("reason",)),
    (re.compile(r"^serve\.tenant\.(?P<tenant>[^.]+)\.(?P<what>[^.]+)$"),
     None, ("tenant",)),          # family derived from <what> below
    (re.compile(r"^served\.(?P<op>[^.]+)\.(?P<rung>[^.]+)$"),
     "served_total", ("op", "rung")),
    (re.compile(r"^faults\.(?P<kind>.+)$"),
     "faults_total", ("kind",)),
)


def _sanitize_name(name: str) -> str:
    return _NAME_BAD.sub("_", name)


def _escape_label(value) -> str:
    return (str(value).replace("\\", "\\\\")
            .replace('"', '\\"').replace("\n", "\\n"))


def _counter_series(name: str) -> tuple[str, str]:
    """Map a dotted counter name to (family, labels-suffix).

    Known families (shed reasons, per-tenant counters, per-rung serve
    counts, fault kinds) fold their trailing segments into labels so a
    scraper sees one time series per family; anything else renders flat.
    """
    for rx, family, label_names in _LABEL_FAMILIES:
        m = rx.match(name)
        if not m:
            continue
        if family is None:  # serve.tenant.<t>.<what> -> per-<what> family
            family = f"serve_tenant_{_sanitize_name(m.group('what'))}_total"
        labels = ",".join(f'{ln}="{_escape_label(m.group(ln))}"'
                          for ln in label_names)
        return f"cme213_{family}", "{" + labels + "}"
    return f"cme213_{_sanitize_name(name)}_total", ""


def merge_snapshots(snaps: dict[str, dict]) -> dict:
    """Fold per-rank snapshots (``{rank-label: snapshot}``) into one
    fleet rollup — the Prometheus-federation aggregate the launcher
    writes for a whole gang.

    Counters sum.  Numeric gauges take the fleet **max** (the
    conservative "worst rank" reading for burn/depth/degraded-style
    gauges; non-numeric gauges are dropped, matching the renderer).
    Histograms sum exact ``count``/``sum``, fold ``min``/``max`` with
    min/max, and take the per-rank max of each window percentile — an
    upper bound, since the retained windows cannot be re-interleaved.
    """
    counters: dict = {}
    gauges: dict = {}
    hists: dict = {}
    for _, snap in sorted(snaps.items(), key=lambda kv: str(kv[0])):
        for k, v in (snap.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, v in (snap.get("gauges") or {}).items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            gauges[k] = v if k not in gauges else max(gauges[k], v)
        for k, h in (snap.get("histograms") or {}).items():
            m = hists.get(k)
            if m is None:
                hists[k] = dict(h)
                continue
            m["count"] = (m.get("count") or 0) + (h.get("count") or 0)
            m["sum"] = round((m.get("sum") or 0) + (h.get("sum") or 0), 6)
            for key, fold in (("min", min), ("max", max),
                              ("p50", max), ("p90", max), ("p99", max)):
                a, b = m.get(key), h.get(key)
                m[key] = b if a is None else (a if b is None else fold(a, b))
            ba, bb = m.get("buckets"), h.get("buckets")
            if ba and bb and len(ba) == len(bb):
                m["buckets"] = [x + y for x, y in zip(ba, bb)]
            elif bb and not ba:
                m["buckets"] = list(bb)
    for h in hists.values():
        h["mean"] = (round((h.get("sum") or 0) / h["count"], 6)
                     if h.get("count") else None)
    return {"counters": dict(sorted(counters.items())),
            "gauges": dict(sorted(gauges.items())),
            "histograms": dict(sorted(hists.items())),
            "ranks": sorted(snaps, key=str)}


def _merge_labels(labels: str, extra: str | None) -> str:
    """Combine a rendered ``{...}`` label block (or ``""``) with one
    extra ``key="value"`` pair."""
    if not extra:
        return labels
    if not labels:
        return "{" + extra + "}"
    return labels[:-1] + "," + extra + "}"


def _summary_compat() -> bool:
    raw = os.environ.get(SUMMARY_COMPAT_ENV, "")
    return raw.strip().lower() not in ("", "0", "false", "no", "off")


def _help_text(family: str) -> str:
    prefix = "cme213_"
    stem = family[len(prefix):] if family.startswith(prefix) else family
    return f"cme213_tpu registry metric {stem.replace('_', ' ')}"


def render_prometheus(snap: dict | None = None, *,
                      fleet: dict[str, dict] | None = None,
                      help_lines: bool = True) -> str:
    """Render a snapshot (default: the live registry) in the Prometheus
    text exposition format.

    Counters become ``cme213_<name>_total``; a few dotted families
    (``serve.shed.<reason>``, ``serve.tenant.<t>.<what>``,
    ``served.<op>.<rung>``, ``faults.<kind>``) fold their variable
    segments into labels.  Numeric gauges render as gauges (non-numeric
    gauge values are skipped — Prometheus has no string samples).
    Histograms render as native cumulative-bucket families:
    ``_bucket{le="<bound>"}`` lines over :data:`BUCKET_BOUNDS` plus
    ``le="+Inf"`` and exact ``_sum``/``_count``.  Setting
    ``CME213_METRICS_SUMMARY_COMPAT`` (truthy) restores the historical
    quantile-summary rendering (``{quantile="0.5|0.9|0.99"}`` lines
    from the retained window); snapshots predating the bucket counts
    fall back to that form per metric.  Every family leads with a
    ``# HELP`` line (suppress with ``help_lines=False``).

    With ``fleet`` — a ``{rank-label: snapshot}`` mapping — the
    federated form renders instead: the :func:`merge_snapshots` rollup
    as the unlabeled series, then every per-rank sample again with a
    ``rank="<label>"`` label, one family block each — the scrape
    surface a replica router/autoscaler consumes.
    """
    fams: dict[str, dict] = {}

    def add(family: str, typ: str, line: str) -> None:
        fam = fams.get(family)
        if fam is None:
            fam = fams[family] = {"type": typ, "samples": []}
        fam["samples"].append(line)

    def emit(s: dict, rank_label: str | None = None) -> None:
        extra = (f'rank="{_escape_label(rank_label)}"'
                 if rank_label is not None else None)
        for name, value in (s.get("counters") or {}).items():
            family, labels = _counter_series(name)
            add(family, "counter",
                f"{family}{_merge_labels(labels, extra)} {value}")
        for name, value in (s.get("gauges") or {}).items():
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                continue
            pname = f"cme213_{_sanitize_name(name)}"
            add(pname, "gauge",
                f"{pname}{_merge_labels('', extra)} {value}")
        compat = _summary_compat()
        for name, h in (s.get("histograms") or {}).items():
            pname = f"cme213_{_sanitize_name(name)}"
            raw = h.get("buckets")
            if not compat and raw and len(raw) == len(BUCKET_BOUNDS) + 1:
                cum = 0
                for bound, n in zip(BUCKET_BOUNDS, raw):
                    cum += n
                    blabels = _merge_labels(
                        f'{{le="{format(bound, "g")}"}}', extra)
                    add(pname, "histogram", f"{pname}_bucket{blabels} {cum}")
                inf_labels = _merge_labels('{le="+Inf"}', extra)
                add(pname, "histogram",
                    f"{pname}_bucket{inf_labels} {cum + raw[-1]}")
                kind = "histogram"
            else:
                for q, key in (("0.5", "p50"), ("0.9", "p90"),
                               ("0.99", "p99")):
                    if h.get(key) is not None:
                        qlabels = _merge_labels(f'{{quantile="{q}"}}', extra)
                        add(pname, "summary", f"{pname}{qlabels} {h[key]}")
                kind = "summary"
            add(pname, kind,
                f"{pname}_sum{_merge_labels('', extra)} {h.get('sum', 0)}")
            add(pname, kind,
                f"{pname}_count{_merge_labels('', extra)} "
                f"{h.get('count', 0)}")

    if fleet is not None:
        emit(merge_snapshots(fleet))
        for label, s in sorted(fleet.items(), key=lambda kv: str(kv[0])):
            emit(s, rank_label=str(label))
    else:
        emit(snapshot() if snap is None else snap)

    lines: list[str] = []
    kind_order = {"counter": 0, "gauge": 1, "summary": 2, "histogram": 2}
    for family in sorted(fams, key=lambda f: (kind_order[fams[f]["type"]],
                                              f)):
        fam = fams[family]
        if help_lines:
            lines.append(f"# HELP {family} {_help_text(family)}")
        lines.append(f"# TYPE {family} {fam['type']}")
        if fam["type"] == "counter":
            lines.extend(sorted(fam["samples"]))
        else:
            lines.extend(fam["samples"])
    return "\n".join(lines) + "\n" if lines else ""


def write_exposition(path: str | None = None) -> str | None:
    """Atomically dump ``render_prometheus()`` to ``path`` (default: the
    ``CME213_METRICS_FILE`` env var).  Returns the path written, or None
    when no destination is configured.  tmp + ``os.replace`` so a scraper
    racing the writer never reads a torn file."""
    path = path or os.environ.get(METRICS_FILE_ENV)
    if not path:
        return None
    text = render_prometheus()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return path


def reset() -> None:
    """Forget every instrument (tests)."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTOGRAMS.clear()


#: exposition paths the atexit writer must leave alone — a launcher that
#: already wrote the gang's *federated* file registers it here so its own
#: single-process snapshot doesn't clobber the fleet view at shutdown
_EXIT_EXPOSITION_SKIP: set = set()


def suppress_exit_exposition(path: str) -> None:
    """Exclude ``path`` from the atexit exposition write (the
    ``metrics-snapshot`` trace record is still emitted)."""
    _EXIT_EXPOSITION_SKIP.add(os.path.abspath(path))


def _emit_exit_snapshot() -> None:
    """At interpreter exit, append one ``metrics-snapshot`` event so sink
    files end with the process's final registry state.  Skipped when the
    registry was never touched (no instruments -> no record)."""
    if not (_COUNTERS or _GAUGES or _HISTOGRAMS):
        return
    from .trace import flush_sink, record_event

    record_event("metrics-snapshot", metrics=snapshot())
    flush_sink()
    dest = os.environ.get(METRICS_FILE_ENV)
    if dest and os.path.abspath(dest) in _EXIT_EXPOSITION_SKIP:
        return
    try:
        write_exposition()
    except OSError:
        pass  # a dead exposition path must not mask the real exit cause


atexit.register(_emit_exit_snapshot)
