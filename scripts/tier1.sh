#!/bin/bash
# Tier-1 verification gate — the exact command ROADMAP.md pins ("Tier-1
# verify"), wrapped so CI and humans run the same thing and pass/fail
# counts are comparable per PR.
#
#   bash scripts/tier1.sh
#
# Runs the non-slow test suite on the CPU backend with a hard timeout,
# echoes a DOTS_PASSED count parsed from the progress dots (robust to a
# crashed worker truncating the summary line), and exits with pytest's
# status.  Collection errors don't abort the run (--continue-on-collection-
# errors) so a broken module costs its own tests, not the whole gate.
set -o pipefail
cd "$(dirname "$0")/.."
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
