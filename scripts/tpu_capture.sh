#!/bin/bash
# Full on-device measurement capture for a round: headline bench (f32 and
# f64), the device-side sweep CSVs, and the Pallas tile sweep.  Run on the
# real TPU (default axon platform) once the tunnel is healthy:
#
#   bash scripts/tpu_capture.sh [outdir]
#
# Every bench.py kernel runs in its own subprocess (bench.py does this
# itself); the run_all sweeps share one process, so a kernel that kills
# the device client aborts the remaining sweeps — run the bisect harness
# (scripts/tpu_pipeline_bisect.py) first if kernels are suspect.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-bench_results}"
mkdir -p "$OUT"

echo "== preflight =="
timeout 120 python -c "
import jax, jax.numpy as jnp
d = jax.devices()[0]
assert d.platform == 'tpu', f'not a TPU: {d}'
(jnp.ones((8, 8)) * 2).block_until_ready()
print('device:', d)
" || { echo "preflight failed — tunnel down?"; exit 1; }

echo "== headline bench (f32) =="
python bench.py 2>"$OUT/bench_f32.stderr.log" | tee "$OUT/bench_f32.json"

echo "== headline bench (f64, XLA kernel) =="
python bench.py --dtype=f64 2>"$OUT/bench_f64.stderr.log" \
    | tee "$OUT/bench_f64.json"

echo "== device sweeps (one process each: a kernel that kills the device"
echo "   client then costs one sweep, not the rest; riskiest last) =="
for sweep in transfer_bandwidth data_bandwidth_vector_length \
             bandwidth_vs_avg_edges scan_bandwidth spmv_suite \
             dist_heat_scaling heat_bandwidth pallas_tile heat_kernels; do
    echo "-- $sweep"
    timeout 2700 python -m cme213_tpu.bench.run_all --out "$OUT" \
        --only "$sweep" || echo "$sweep: FAILED (continuing)"
done

echo "== f64 heat rows (reference's double 4th-order axis) =="
JAX_ENABLE_X64=1 python - <<'EOF'
from cme213_tpu.bench import sweeps
import sys
rows = sweeps.heat_sweep(sizes=(4000,), orders=(2, 4, 8), iters=100,
                         dtype="f64")
sweeps.write_csv(rows, sys.argv[1] if len(sys.argv) > 1
                 else "bench_results/heat_bandwidth_f64.csv")
print(f"f64 rows: {len(rows)}")
EOF

echo "capture complete: $OUT"
