"""Distributed 2-D heat solve — shard_map domain decomposition.

TPU-native redesign of the reference's MPI heat engine
(``hw/hw5/programming/2dHeat.cpp``): the interior grid (ny, nx) is sharded
over a 1-D ("y" stripes, gridMethod=1) or 2-D ("y","x" blocks, gridMethod=2)
device mesh; each step exchanges ``border_size``-wide halos via
``lax.ppermute`` (see ``halo.py``) and applies the order-2/4/8 stencil to the
local block.  The whole iteration loop runs inside one ``shard_map``-of-``jit``
so no resharding happens between steps (the functional analog of the
reference's persistent per-rank buffers).

Two step variants, selected by ``SimParams.synchronous`` exactly like the
reference's ``syncComputation``/``asyncComputation``:

- **sync** (``2dHeat.cpp:583-694``): exchange → assemble padded block →
  stencil over the whole local interior.
- **overlap** (``:696-815``): the stencil over the halo-independent inner
  region is computed *from the raw block with no data dependence on the
  ppermute results*, so XLA's scheduler can run collective-permute and inner
  compute concurrently (the structural form of comm/compute overlap,
  strategy P11); the four halo-adjacent bands are then computed from the
  padded block and assembled around the inner region.

Both variants are arithmetically identical per cell (same expression per
output), so sync-vs-overlap and N-vs-1-device results match to the ULP.

Corner note: the heat stencils are axis-separable (no diagonal taps), so
corner halos are never read; the exchange order (y slabs first, then x slabs
of the y-padded block) still fills corners with the diagonal neighbor's data,
mirroring the reference's full-column pack buffers (``2dHeat.cpp:456-462``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import SimParams
from ..grid import make_initial_grid, interior
from ..ops.stencil import BORDER_FOR_ORDER, stencil_interior
from .halo import pad_with_halos
from .mesh import shard_map


def _pad_axis0(block, axis_name, axis_size, border, lo_fill, hi_fill):
    if axis_size > 1:
        return pad_with_halos(block, axis_name, axis_size, border,
                              lo_fill, hi_fill)
    w = block.shape[1]
    lo = jnp.full((border, w), lo_fill, block.dtype)
    hi = jnp.full((border, w), hi_fill, block.dtype)
    return jnp.concatenate([lo, block, hi], axis=0)


def _assemble_padded(block, params: SimParams, y_size: int, x_size: int,
                     border: int | None = None):
    """Local block + y halos + x halos (BC fill at physical boundaries).

    ``border`` defaults to the stencil border; the communication-avoiding
    path passes K = k·border.  The y-then-x order encodes the corner-fill
    invariant (see module header)."""
    b = params.border_size if border is None else border
    ypad = _pad_axis0(block, "y", y_size, b, params.bc_bottom, params.bc_top)
    xpad = _pad_axis0(ypad.T, "x", x_size, b, params.bc_left, params.bc_right)
    return xpad.T


def _reimpose_ghost(new_block, params: SimParams, y_size: int, x_size: int):
    """Reset ghost rows/columns (padding beyond the true ny×nx domain, used
    to support grid sizes that don't divide the mesh — the analog of the
    reference's remainder-on-last-rank layout, ``2dHeat.cpp:284-307``) to
    the top/right BC values.  Held at BC each step, the first ``b`` ghost
    lines act as the Dirichlet band for the true domain edge."""
    ny_loc, nx_loc = new_block.shape
    dtype = new_block.dtype
    if y_size * ny_loc != params.ny:
        gr = (lax.axis_index("y") * ny_loc
              + jax.lax.broadcasted_iota(jnp.int32, new_block.shape, 0))
        new_block = jnp.where(gr >= params.ny,
                              jnp.asarray(params.bc_top, dtype), new_block)
    if x_size * nx_loc != params.nx:
        gc = ((lax.axis_index("x") if x_size > 1 else 0) * nx_loc
              + jax.lax.broadcasted_iota(jnp.int32, new_block.shape, 1))
        new_block = jnp.where(gc >= params.nx,
                              jnp.asarray(params.bc_right, dtype), new_block)
    return new_block


def _sync_local_step(block, params: SimParams, y_size: int, x_size: int):
    padded = _assemble_padded(block, params, y_size, x_size)
    new = stencil_interior(padded, params.order, params.xcfl, params.ycfl)
    return _reimpose_ghost(new, params, y_size, x_size)


def _overlap_local_step(block, params: SimParams, y_size: int, x_size: int):
    b = params.border_size
    ny, nx = block.shape
    # inner region needs no halo: computed straight from the raw block, with
    # no dependence on the ppermute results — overlappable by the scheduler
    # (the analog of computing the offset-2·borderSize interior while
    # MPI_Isend/Irecv are in flight, 2dHeat.cpp:713-721)
    inner = stencil_interior(block, params.order, params.xcfl, params.ycfl)
    padded = _assemble_padded(block, params, y_size, x_size)
    st = partial(stencil_interior, order=params.order, xcfl=params.xcfl,
                 ycfl=params.ycfl)
    # four halo-adjacent bands (2dHeat.cpp:724-745): local rows [0,b) and
    # [ny-b,ny) full width; local cols [0,b) and [nx-b,nx) for middle rows.
    # padded index = local index + b.
    bottom = st(padded[0:3 * b, :])                    # rows [0, b)
    top = st(padded[ny - b:ny + 2 * b, :])             # rows [ny-b, ny)
    left = st(padded[b:b + ny, 0:3 * b])               # cols [0, b), mid rows
    right = st(padded[b:b + ny, nx - b:nx + 2 * b])
    middle = jnp.concatenate([left, inner, right], axis=1)
    new = jnp.concatenate([bottom, middle, top], axis=0)
    return _reimpose_ghost(new, params, y_size, x_size)


def _multistep_local_step(block, params: SimParams, y_size: int, x_size: int,
                          k: int):
    """k timesteps per halo exchange (communication-avoiding stencil).

    Exchanges K = k·border-wide halos once, then applies the stencil k
    times locally; the validity margin shrinks by ``border`` per sub-step,
    exactly covering the extra halo — the mesh-scale form of the Pallas
    temporal-blocking kernel (``ops/stencil_pallas.run_heat_multistep``),
    cutting ppermute message count by k at the cost of k·border redundant
    boundary rows of compute.  Physical-boundary and ghost cells are
    re-imposed between sub-steps keyed on global coordinates, so results
    are bitwise identical to the k=1 paths.
    """
    b = params.border_size
    K = k * b
    ny_loc, nx_loc = block.shape
    # K-wide halo assembly; BC fill replicates the Dirichlet band values an
    # infinite border would hold
    p = _assemble_padded(block, params, y_size, x_size, border=K)
    H, W = p.shape
    # global halo-grid coords of padded local cell (l_r, l_c)
    gy0 = lax.axis_index("y") * ny_loc + b - K
    gx0 = (lax.axis_index("x") if x_size > 1 else 0) * nx_loc + b - K
    gr = gy0 + jax.lax.broadcasted_iota(jnp.int32, (H, W), 0)
    gc = gx0 + jax.lax.broadcasted_iota(jnp.int32, (H, W), 1)
    dtype = block.dtype
    for _ in range(k):
        inner = stencil_interior(p, params.order, params.xcfl, params.ycfl)
        p = p.at[b:-b, b:-b].set(inner)
        # Dirichlet bands; ghost rows/cols beyond the true ny×nx domain
        # merge into the top/right conditions (they are held at those BC
        # values, acting as the domain-edge band — see _reimpose_ghost)
        p = jnp.where(gr < b, jnp.asarray(params.bc_bottom, dtype), p)
        p = jnp.where(gr >= b + params.ny,
                      jnp.asarray(params.bc_top, dtype), p)
        p = jnp.where(gc < b, jnp.asarray(params.bc_left, dtype), p)
        p = jnp.where(gc >= b + params.nx,
                      jnp.asarray(params.bc_right, dtype), p)
    return p[K:K + ny_loc, K:K + nx_loc]


def _multistep_local_step_pallas(block, params: SimParams, y_size: int,
                                 x_size: int, k: int, tile_y: int,
                                 interpret: bool):
    """The tuned-kernel form of ``_multistep_local_step``: one Pallas call
    applies k timesteps to the K-padded local block (the hw5 pattern of
    running the hw2 optimized kernel under the communication layer).
    Bitwise-equal to the XLA path — same taps, same accumulation order,
    same global-coordinate BC masking."""
    from ..ops.stencil_pipeline import stencil_local_multistep

    b = params.border_size
    K = k * b
    ny_loc, nx_loc = block.shape
    p = _assemble_padded(block, params, y_size, x_size, border=K)
    gy0 = lax.axis_index("y") * ny_loc + b - K
    gx0 = (lax.axis_index("x") if x_size > 1 else 0) * nx_loc + b - K
    out = stencil_local_multistep(
        p, gy0, gx0, params.ny, params.nx, params.order,
        float(params.xcfl), float(params.ycfl), params.bc, k=k,
        tile_y=tile_y, interpret=interpret)
    return out[K:K + ny_loc, K:K + nx_loc]


def distributed_heat_step(params: SimParams, mesh: Mesh, overlap: bool = False):
    """Build the sharded single-step function ``u (ny,nx) -> u'`` (interior
    arrays, sharded over ``mesh``)."""
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    y_size = axes.get("y", 1)
    x_size = axes.get("x", 1)
    spec = P("y", "x" if "x" in axes else None)
    local = _overlap_local_step if overlap else _sync_local_step

    def step(u):
        return shard_map(
            lambda blk: local(blk, params, y_size, x_size),
            mesh=mesh, in_specs=(spec,), out_specs=spec,
        )(u)

    return step, spec


@partial(jax.jit, static_argnames=("params", "mesh", "iters", "overlap",
                                   "steps_per_exchange", "local_kernel",
                                   "tile_y"),
         donate_argnums=(0,))
def _run(u, params, mesh, iters, overlap, steps_per_exchange=1,
         local_kernel="xla", tile_y=128):
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    y_size = axes.get("y", 1)
    x_size = axes.get("x", 1)
    spec = P("y", "x" if "x" in axes else None)
    k = steps_per_exchange
    if local_kernel == "pallas":
        interpret = jax.devices()[0].platform != "tpu"
        local = partial(_multistep_local_step_pallas, k=k, tile_y=tile_y,
                        interpret=interpret)
    elif k > 1:
        local = partial(_multistep_local_step, k=k)
    else:
        local = _overlap_local_step if overlap else _sync_local_step

    def sharded_loop(blk):
        return lax.fori_loop(
            0, iters // k, lambda _, g: local(g, params, y_size, x_size),
            blk)

    # check_vma=False for the Pallas local kernel: varying-across-mesh
    # tracking through interpret-mode pallas_call trips a lowering-cache
    # bug, and the kernel neither uses collectives nor crosses shards
    return shard_map(sharded_loop, mesh=mesh,
                     in_specs=(spec,), out_specs=spec,
                     check_vma=local_kernel != "pallas")(u)


def prepare_distributed_heat(params: SimParams, mesh: Mesh,
                             iters: int | None = None, dtype=jnp.float32,
                             overlap: bool | None = None,
                             steps_per_exchange: int = 1,
                             local_kernel: str = "xla"):
    """Set up a distributed solve and return ``(iterate, overlap_used,
    steps_per_exchange_used)``.

    ``steps_per_exchange`` > 1 selects the communication-avoiding path
    (k local sub-steps per K=k·border halo exchange,
    ``_multistep_local_step``); it falls back to 1 when shards are thinner
    than K, when ``iters`` doesn't divide by k, or combined with
    ``overlap`` (fewer exchanges subsume the overlap split).

    ``iterate()`` uploads a fresh initial grid, runs the full iteration
    loop on device, and returns ``(seconds, out)`` where ``seconds`` times
    *only* the device loop (the analog of the reference's ``MPI_Wtime``
    bracket around the computation, ``2dHeat.cpp:832-841``) — host-side
    grid assembly and the upload are excluded.  It can be called
    repeatedly (warmup + timed runs hit the same jit cache entry).

    ``overlap_used`` reports the scheme that will actually run:
    ``overlap=True`` falls back to the sync path when the local blocks are
    too thin for the interior/band split, and callers recording
    sync-vs-async comparisons need the resolved value.
    """
    import time as _time

    iters = params.iters if iters is None else iters
    overlap = (not params.synchronous) if overlap is None else overlap
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    y_size = axes.get("y", 1)
    x_size = axes.get("x", 1)
    b = params.border_size
    # non-divisible grids: pad with ghost rows/cols held at the top/right BC
    # each step (the reference's remainder-rank layout, 2dHeat.cpp:284-307,
    # expressed as padding)
    ny_pad = -(-params.ny // y_size) * y_size
    nx_pad = -(-params.nx // x_size) * x_size
    ny_loc = ny_pad // y_size
    nx_loc = nx_pad // x_size
    if ny_loc < b or nx_loc < b:
        # a halo slab would span more than one neighbor shard — same
        # local-extent constraint the reference's per-rank layout implies
        raise ValueError(
            f"local block ({ny_loc}×{nx_loc}) thinner than the stencil "
            f"border ({b}); use fewer devices or a larger grid")
    if overlap and (ny_loc < 2 * b or nx_loc < 2 * b):
        # local blocks too thin for the interior/band split — the overlap
        # decomposition needs ≥ 2·border rows/cols per shard
        overlap = False

    if local_kernel not in ("xla", "pallas"):
        raise ValueError(f"unknown local_kernel {local_kernel!r} "
                         "(expected 'xla' or 'pallas')")
    if local_kernel == "pallas":
        overlap = False  # the Pallas local step subsumes the overlap split
    k = steps_per_exchange
    if k > 1 and (overlap or iters % k
                  or ny_loc < k * b or nx_loc < k * b):
        k = 1  # communication-avoiding path ineligible: fall back
    tile_y = 128
    if local_kernel == "pallas":
        from ..ops.stencil_pipeline import pick_pipeline_tile

        tile_y = pick_pipeline_tile(ny_loc + 2 * k * b, k, params.order,
                                    target=128)

    full0 = make_initial_grid(params, dtype=dtype)
    u0 = np.array(interior(full0, b))
    if ny_pad > params.ny:
        pad_rows = np.full((ny_pad - params.ny, u0.shape[1]), params.bc_top,
                           u0.dtype)
        u0 = np.concatenate([u0, pad_rows], axis=0)
    if nx_pad > params.nx:
        pad_cols = np.full((u0.shape[0], nx_pad - params.nx), params.bc_right,
                           u0.dtype)
        u0 = np.concatenate([u0, pad_cols], axis=1)
    spec = P("y", "x" if "x" in axes else None)
    sharding = NamedSharding(mesh, spec)

    def iterate():
        # fresh upload each call: _run donates its input buffer
        u = jax.device_put(jnp.asarray(u0), sharding)
        jax.block_until_ready(u)
        t0 = _time.perf_counter()
        out = _run(u, params, mesh, iters, overlap, steps_per_exchange=k,
                   local_kernel=local_kernel, tile_y=tile_y)
        jax.block_until_ready(out)
        return _time.perf_counter() - t0, out

    return iterate, overlap, k


def _mesh_layout(params: SimParams, mesh: Mesh):
    """(y_size, x_size, ny_loc, nx_loc, spec) for ``params`` on ``mesh``,
    with the same local-extent validation as ``prepare_distributed_heat``
    (ghost padding supports non-divisible grids)."""
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    y_size = axes.get("y", 1)
    x_size = axes.get("x", 1)
    b = params.border_size
    ny_loc = -(-params.ny // y_size)
    nx_loc = -(-params.nx // x_size)
    if ny_loc < b or nx_loc < b:
        raise ValueError(
            f"local block ({ny_loc}×{nx_loc}) thinner than the stencil "
            f"border ({b}); use fewer devices or a larger grid")
    return y_size, x_size, ny_loc, nx_loc, P("y", "x" if "x" in axes else None)


def _pad_interior_for_mesh(u: np.ndarray, params: SimParams,
                           y_size: int, x_size: int) -> np.ndarray:
    """Ghost-pad a true (ny, nx) interior so it divides over the mesh —
    held at the top/right BC values each step (the reference's remainder-
    rank layout expressed as padding)."""
    ny_pad = -(-params.ny // y_size) * y_size
    nx_pad = -(-params.nx // x_size) * x_size
    if ny_pad > params.ny:
        pad_rows = np.full((ny_pad - params.ny, u.shape[1]), params.bc_top,
                           u.dtype)
        u = np.concatenate([u, pad_rows], axis=0)
    if nx_pad > params.nx:
        pad_cols = np.full((u.shape[0], nx_pad - params.nx), params.bc_right,
                           u.dtype)
        u = np.concatenate([u, pad_cols], axis=1)
    return u


def run_distributed_heat_supervised(params: SimParams, mesh: Mesh,
                                    ckpt_dir: str, ckpt_every: int = 0,
                                    iters: int | None = None,
                                    dtype=jnp.float32,
                                    overlap: bool | None = None,
                                    resume: bool = True,
                                    heartbeat=None,
                                    commit_timeout: float = 120.0
                                    ) -> np.ndarray:
    """The supervised form of ``run_distributed_heat``: the solve runs in
    epochs of ``ckpt_every`` iterations, each ending in an epoch-committed
    distributed checkpoint (``dist/ckpt.py``) and a heartbeat carrying the
    step counter (``dist/supervisor.py``) — the two hooks gang supervision
    needs to detect a dead or frozen rank and relaunch the whole gang from
    the last globally consistent state.

    ``resume`` loads the newest valid commit in ``ckpt_dir`` (this is how
    a gang restart continues; ``CME213_RESUME`` gates it from the
    launcher).  Resume is **elastic**: the commit records the shard map,
    so the global grid is reassembled and re-decomposed for *this* mesh
    even when the committed run used a different device count or
    ``GridMethod`` — and on the sync path every decomposition is bitwise-
    identical per cell, so the recovered solve equals an uninterrupted one
    exactly.  ``faults.maybe_kill_rank`` guards each epoch boundary so
    ``CME213_FAULTS=rankkill:<rank>:<epoch>`` injects a deterministic
    mid-solve death for recovery tests.

    An epoch chunk that dies ``RESOURCE_EXHAUSTED`` (real, or
    ``CME213_FAULTS=oom:heat_chunk``) halves ``ckpt_every``, re-shards
    from the last committed state, and retries — the supervised form of
    the checkpointed solve's chunk-shrink response (bitwise-neutral on
    the sync path, like every other re-decomposition).

    Returns the final full halo grid (gy, gx) as numpy, like
    ``run_distributed_heat``.
    """
    import time

    from ..core import metrics
    from ..core.faults import maybe_kill_rank, maybe_oom
    from ..core.numerics import (ConvergenceTracker, progress_from_states,
                                 state_snapshot)
    from ..core.resilience import FailureKind, classify_failure
    from ..core.trace import record_event
    from .ckpt import check_meta, commit_epoch, load_latest_commit

    iters = params.iters if iters is None else iters
    ckpt_every = ckpt_every or iters
    overlap = (not params.synchronous) if overlap is None else overlap
    y_size, x_size, ny_loc, nx_loc, spec = _mesh_layout(params, mesh)
    b = params.border_size
    if overlap and (ny_loc < 2 * b or nx_loc < 2 * b):
        overlap = False
    meta = {"kind": "heat2d", "ny": params.ny, "nx": params.nx,
            "order": params.order, "border": b,
            "grid_method": int(params.grid_method),
            "dtype": np.dtype(dtype).name}
    process_id, process_count = 0, 1
    if jax.process_count() > 1:  # real multi-process gang
        process_id, process_count = jax.process_index(), jax.process_count()

    start, epoch = 0, 0
    loaded = load_latest_commit(ckpt_dir) if resume else None
    if loaded is not None:
        manifest, interior_grid = loaded
        check_meta(manifest, **meta)
        start, epoch = manifest["step"], manifest["epoch"]
        u_host = _pad_interior_for_mesh(
            np.asarray(interior_grid, dtype=np.dtype(dtype)),
            params, y_size, x_size)
    else:
        full0 = make_initial_grid(params, dtype=dtype)
        u_host = _pad_interior_for_mesh(np.array(interior(full0, b)),
                                        params, y_size, x_size)

    sharding = NamedSharding(mesh, spec)
    u = jax.device_put(jnp.asarray(u_host, dtype), sharding)
    if heartbeat is not None:
        heartbeat.beat(start)
    it = start
    # per-epoch convergence trace: the supervised solve's residual,
    # delta-norm, and iterations/s ride solver-progress events so a
    # stalled gang is visible in `top` before the supervisor's timeout
    tracker = ConvergenceTracker("heat2d")
    while it < iters:
        # deterministic kill window: `step` counts committed epochs, so
        # rankkill:<rank>:<e> always dies holding exactly e commits
        maybe_kill_rank(step=epoch)
        k = min(ckpt_every, iters - it)
        # host snapshot before the epoch: the sharded step may donate
        # its input buffers, and the residual needs the pre-step state
        prev = state_snapshot(u)
        t0 = time.perf_counter()
        try:
            maybe_oom("heat_chunk")
            u_new = _run(u, params, mesh, k, overlap)
            jax.block_until_ready(u_new)
        except Exception as e:  # noqa: BLE001 — classify, then decide
            if classify_failure(e) is not FailureKind.RESOURCE or k <= 1:
                raise
            ckpt_every = max(1, k // 2)
            metrics.counter("admission.chunk_shrunk").inc()
            record_event("chunk-shrunk", op="heat2d", from_size=k,
                         to_size=ckpt_every, reason=type(e).__name__)
            # the chunk may have consumed its donated shard buffers —
            # rebuild from the last committed state (or the initial grid)
            loaded = load_latest_commit(ckpt_dir)
            if loaded is not None:
                manifest, interior_grid = loaded
                check_meta(manifest, **meta)
                it, epoch = manifest["step"], manifest["epoch"]
                u_host = _pad_interior_for_mesh(
                    np.asarray(interior_grid, dtype=np.dtype(dtype)),
                    params, y_size, x_size)
            else:
                it, epoch = 0, 0
                full0 = make_initial_grid(params, dtype=dtype)
                u_host = _pad_interior_for_mesh(
                    np.array(interior(full0, b)), params, y_size, x_size)
            u = jax.device_put(jnp.asarray(u_host, dtype), sharding)
            continue
        progress_from_states(tracker, it + k, prev, u_new, k,
                             time.perf_counter() - t0)
        u = u_new
        it += k
        epoch += 1
        commit_epoch(ckpt_dir, epoch, it, u,
                     true_shape=(params.ny, params.nx), meta=meta,
                     process_id=process_id, process_count=process_count,
                     timeout=commit_timeout)
        if heartbeat is not None:
            heartbeat.beat(it)
    out = np.asarray(u)
    final = np.array(make_initial_grid(params, dtype=dtype))
    final[b:-b, b:-b] = out[:params.ny, :params.nx]
    return final


def _probe_params(params: SimParams, mesh: Mesh, k: int) -> SimParams:
    """A small probe configuration compatible with ``mesh`` and the
    communication-avoiding factor ``k``: every shard keeps ≥ K = k·border
    rows/cols, mirroring the caller's order and grid method."""
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    y_size = axes.get("y", 1)
    x_size = axes.get("x", 1)
    b = params.border_size
    loc = max(8, k * b)
    return SimParams(nx=max(40, x_size * loc), ny=y_size * loc,
                     order=params.order, iters=4 * k, bc_top=2.0,
                     bc_left=0.5, bc_bottom=1.0, bc_right=3.0,
                     grid_method=params.grid_method)


def _gated_heat_config(params: SimParams, mesh: Mesh, local_kernel: str,
                       k: int, dtype) -> tuple[str, int]:
    """Conformance-gate the distributed heat rungs before serving:

    - the Pallas local kernel probes against the XLA local kernel at the
      *same* communication-avoiding factor (its bitwise contract);
    - the k>1 exchange-every-k path probes against the k=1 path
      (``_multistep_local_step``'s bitwise contract);

    each on a small distributed solve on this mesh, demoting to the XLA
    local kernel / k=1 on divergence — the hw5 N-vs-1 offline comparison
    moved into the serving path.  Verdicts cache per process × order ×
    k × mesh shape."""
    from ..core import conformance, metrics
    from ..core.resilience import FailureKind
    from ..core.trace import record_event

    def probe(kernel: str, kk: int, ref_kernel: str, ref_k: int) -> bool:
        p = _probe_params(params, mesh, max(kk, ref_k))

        def solve(kern, sk):
            return lambda: run_distributed_heat(
                p, mesh, dtype=dtype, overlap=False, steps_per_exchange=sk,
                local_kernel=kern, conformance=False)

        rung = f"{kernel}-k{kk}"
        shape_class = (f"order{params.order}/k{kk}/"
                       f"mesh{'x'.join(str(s) for s in mesh.devices.shape)}")
        return conformance.check("dist_heat", rung, shape_class=shape_class,
                                 candidate=solve(kernel, kk),
                                 reference=solve(ref_kernel, ref_k)).ok

    def demote(rung: str) -> None:
        metrics.counter("fallback.demotions").inc()
        record_event("rung-failed", op="dist_heat", rung=rung,
                     kind=FailureKind.WRONG_ANSWER.value,
                     error="ConformanceFailed")

    if local_kernel == "pallas" and not probe("pallas", k, "xla", k):
        demote(f"pallas-k{k}")
        local_kernel = "xla"
    if local_kernel == "xla" and k > 1 and not probe("xla", k, "xla", 1):
        demote(f"xla-k{k}")
        k = 1
    return local_kernel, k


def run_distributed_heat(params: SimParams, mesh: Mesh,
                         iters: int | None = None, dtype=jnp.float32,
                         overlap: bool | None = None,
                         steps_per_exchange: int = 1,
                         local_kernel: str = "xla",
                         conformance: bool = True) -> np.ndarray:
    """Full distributed solve.  Returns the final full halo grid (gy, gx)
    as numpy, for direct comparison with the single-device solver and the
    reference's per-rank ``grid{rank}_final.txt`` methodology (SURVEY §4.4).

    ``overlap`` defaults to ``not params.synchronous`` (hw5 ``sync`` flag).
    ``local_kernel="pallas"`` runs the tuned pipeline kernel per shard
    (the hw5 pattern: the optimized hw2 kernel under the comm layer).

    With ``conformance`` (default), the non-reference rungs — the Pallas
    local kernel, and the k>1 communication-avoiding exchange — are
    probed on first use against the single-device reference and demoted
    (``WRONG_ANSWER``) on divergence: the hw5 N-vs-1 comparison, moved
    from offline methodology into the serving path.  Pass
    ``conformance=False`` to pin the requested kernel (kernel-equality
    tests; bench rows are data).
    """
    if conformance and (local_kernel == "pallas" or steps_per_exchange > 1):
        local_kernel, steps_per_exchange = _gated_heat_config(
            params, mesh, local_kernel, steps_per_exchange, dtype)
    iterate, _, _ = prepare_distributed_heat(
        params, mesh, iters=iters, dtype=dtype, overlap=overlap,
        steps_per_exchange=steps_per_exchange, local_kernel=local_kernel)
    _, out = iterate()
    b = params.border_size
    final = np.array(make_initial_grid(params, dtype=dtype))
    final[b:-b, b:-b] = np.asarray(out)[:params.ny, :params.nx]
    return final
