"""State snapshotting and checkpoint/resume.

The reference's only persistence is debug/correctness snapshotting: text grid
dumps at init and final (``Grid::saveStateToFile``,
``hw/hw2/programming/2dHeat.cu:350-359``, per-rank in hw5 ``:549-557``), used
for BC debugging and offline N-vs-1 diffing (SURVEY §5).  This module keeps
that text-dump path (``grid/grid.py``) and adds the hardened binary
checkpoint/resume layer the reference lacked:

- **Checksummed payload**: every ``.npz`` carries a CRC32 over step + array
  names/dtypes/shapes/bytes (``__crc``); a mismatch is treated exactly like
  a torn file.
- **Last-good retention**: a successful save first rotates the previous
  checkpoint to ``<path>.prev``, so one corrupted write never destroys the
  only resume point.
- **Corrupt-file quarantine**: a truncated/foreign/checksum-failing file is
  moved to ``<candidate>.corrupt`` (never deleted — it's evidence) with a
  warning and a structured ``checkpoint-quarantine`` trace event, and the
  loader falls back to ``.prev``.
- **Pytree states**: ``run_with_checkpoints`` accepts any array pytree (the
  heat solver's ``(grid, halo)``-style states), flattened into per-leaf
  entries plus a pickled treedef.
- **Abort-to-last-good**: an optional ``guard`` (e.g.
  ``resilience.all_finite``) runs on each chunk result *outside* the jitted
  hot loop; a tripped guard rolls the state back to the last good
  checkpoint and retries the chunk (bounded), instead of writing a poisoned
  checkpoint or aborting the solve.
"""

from __future__ import annotations

import os
import pickle
import warnings
import zipfile
import zlib

import numpy as np

from . import metrics
from .trace import record_event, span

#: suffix of quarantined (corrupt) checkpoint files
CORRUPT_SUFFIX = ".corrupt"
#: suffix of the retained previous-good checkpoint
PREV_SUFFIX = ".prev"

_TREE_KEY = "__treedef"


class CheckpointCorrupt(RuntimeError):
    """The file exists but fails structural or checksum validation."""


def _payload_crc(step: int, arrays: dict) -> int:
    """CRC32 over step + sorted (name, dtype, shape, bytes) — the torn-write
    detector.  Cheap relative to the ``np.savez`` deflate pass."""
    crc = zlib.crc32(str(int(step)).encode())
    for k in sorted(arrays):
        a = np.ascontiguousarray(arrays[k])
        crc = zlib.crc32(k.encode(), crc)
        crc = zlib.crc32(str(a.dtype).encode(), crc)
        crc = zlib.crc32(str(a.shape).encode(), crc)
        crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFFFFFF


def save_checkpoint(path: str, step: int, **arrays) -> int:
    """Atomic write of named arrays + step counter + payload checksum,
    rotating any existing checkpoint to ``<path>.prev`` (last-good
    retention).  Returns the payload CRC32, so callers building commit
    manifests (``dist/ckpt.py``) can record it without re-reading the
    file."""
    from .faults import maybe_truncate_file

    arrays = {k: np.asarray(v) for k, v in arrays.items()}
    crc = _payload_crc(step, arrays)
    tmp = path + ".tmp"
    np.savez(tmp, __step=np.int64(step), __crc=np.uint32(crc), **arrays)
    # np.savez appends .npz to names without an extension
    if not tmp.endswith(".npz") and os.path.exists(tmp + ".npz"):
        tmp = tmp + ".npz"
    maybe_truncate_file(tmp)  # injected torn write (no-op without faults)
    if os.path.exists(path):
        os.replace(path, path + PREV_SUFFIX)
    os.replace(tmp, path)
    return crc


def read_checkpoint(path: str, expect_crc: int | None = None):
    """(step, arrays, crc) from one candidate file; raises
    CheckpointCorrupt (or a zip/npz parse error) on anything invalid —
    no quarantine side effects, so commit-manifest validation
    (``dist/ckpt.py``) can probe shard files and fall back on its own
    terms.  ``expect_crc`` additionally pins the payload to a manifest-
    recorded checksum."""
    with np.load(path, allow_pickle=False) as z:
        if "__step" not in z.files:
            raise CheckpointCorrupt("missing __step (foreign npz?)")
        step = int(z["__step"])
        arrays = {k: z[k] for k in z.files if k not in ("__step", "__crc")}
        crc = int(z["__crc"]) if "__crc" in z.files else None
        if crc is not None:  # pre-checksum files stay loadable
            if crc != _payload_crc(step, arrays):
                raise CheckpointCorrupt("payload checksum mismatch")
    if expect_crc is not None and crc != expect_crc:
        raise CheckpointCorrupt(
            f"payload crc {crc} != manifest-recorded {expect_crc}")
    return step, arrays, crc


def _read_checkpoint(path: str):
    """(step, arrays) from one candidate file; raises CheckpointCorrupt (or
    a zip/npz parse error) on anything invalid."""
    step, arrays, _ = read_checkpoint(path)
    return step, arrays


def load_checkpoint(path: str):
    """Returns (step, {name: array}) or None if absent/unrecoverable.

    A corrupt/truncated/foreign candidate is quarantined to
    ``<candidate>.corrupt`` with a warning instead of raising, and the
    loader falls back to the retained ``<path>.prev``.
    """
    for candidate in (path, path + PREV_SUFFIX):
        if not os.path.exists(candidate):
            continue
        try:
            return _read_checkpoint(candidate)
        except (zipfile.BadZipFile, CheckpointCorrupt, KeyError, ValueError,
                OSError, EOFError) as e:
            quarantine = candidate + CORRUPT_SUFFIX
            os.replace(candidate, quarantine)
            metrics.counter("checkpoint.quarantines").inc()
            record_event("checkpoint-quarantine", path=candidate,
                         quarantined_to=quarantine,
                         error=type(e).__name__, message=str(e)[:200])
            warnings.warn(
                f"quarantined corrupt checkpoint {candidate} -> "
                f"{quarantine} ({type(e).__name__}: {e})", stacklevel=2)
    return None


# ------------------------------------------------------------- pytree layer

def _flatten_state(state) -> dict:
    """Pytree state -> named-array dict (per-leaf entries + pickled
    treedef).  A bare ndarray keeps the legacy single-``state`` layout so
    old checkpoints and new ones stay mutually readable."""
    if isinstance(state, np.ndarray):
        return {"state": state}
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state)
    if len(leaves) == 1 and leaves[0] is state:
        return {"state": np.asarray(state)}  # single-array leaf (jnp array)
    arrays = {f"__leaf{i}": np.asarray(v) for i, v in enumerate(leaves)}
    arrays[_TREE_KEY] = np.frombuffer(pickle.dumps(treedef), dtype=np.uint8)
    return arrays


def _unflatten_state(arrays: dict):
    if _TREE_KEY in arrays:
        import jax

        treedef = pickle.loads(arrays[_TREE_KEY].tobytes())
        leaves = [arrays[f"__leaf{i}"]
                  for i in range(len(arrays) - 1)]
        return jax.tree_util.tree_unflatten(treedef, leaves)
    return arrays["state"]  # legacy single-ndarray layout


def save_state_checkpoint(path: str, step: int, state) -> None:
    """``save_checkpoint`` for an arbitrary array pytree state."""
    save_checkpoint(path, step, **_flatten_state(state))


def run_with_checkpoints(step_fn, state, total_iters: int, path: str,
                         every: int = 0, guard=None, op: str = "run",
                         max_retries: int = 1, chunk_op: str | None = None,
                         tracker=None):
    """Drive ``state = step_fn(state, k_iters)`` in checkpointed chunks,
    resuming from ``path`` if a checkpoint exists.

    ``step_fn(state, k)`` must advance the state by k iterations; ``state``
    may be any array pytree (restored with its structure).  ``guard`` is an
    optional host-side predicate on the chunk result (run *outside* any
    jitted loop — e.g. ``resilience.all_finite``); when it returns False
    the chunk result is discarded, the state rolls back to the last good
    checkpoint, and the chunk is retried up to ``max_retries`` times before
    ``NonFiniteError`` is raised.  ``op`` names this solve for fault
    injection (``nan:<op>:<nth>`` poisons the Nth chunk) and trace events.
    Every accepted chunk feeds a ``core.numerics.ConvergenceTracker``
    (one ``solver-progress`` event per chunk: residual, delta-norm,
    iterations/s); pass ``tracker`` to tune the stall policy or read the
    STALLED verdict back after the solve.

    **Memory-aware degradation**: a chunk that dies RESOURCE-classified
    (an HBM ``RESOURCE_EXHAUSTED``, real or injected via
    ``oom:<chunk_op>``; ``chunk_op`` defaults to ``<op>_chunk``) halves
    the chunk length and retries from the last good checkpoint instead of
    aborting — chunking is arithmetic-neutral (every iteration runs the
    same program regardless of chunk boundaries), so a shrunk-and-retried
    solve stays bitwise equal to an uninterrupted one.  Each halving
    emits a ``chunk-shrunk`` event; a RESOURCE failure at chunk length 1
    re-raises (no smaller program exists).
    """
    import time

    from . import flight
    from .faults import maybe_oom, maybe_poison
    from .numerics import (ConvergenceTracker, progress_from_states,
                           state_snapshot)
    from .resilience import FailureKind, NonFiniteError, classify_failure

    # a checkpointed solve is a *long* solve: arm the flight recorder
    # (only when CME213_FLIGHT_DIR opts in — this is a library path)
    flight.install_from_env()
    chunk_op = chunk_op or f"{op}_chunk"
    start = 0
    loaded = load_checkpoint(path)
    if loaded is not None:
        start, arrays = loaded
        state = _unflatten_state(arrays)
    elif guard is not None:
        # a guarded solve needs a step-0 resume point: a first-chunk
        # blow-up must roll back to the initial state, not abort
        save_state_checkpoint(path, 0, state)
    every = every or total_iters
    it = start
    retries = 0
    # convergence tracing: one solver-progress event per accepted chunk
    # (residual = relative state change), so a stalling long solve is
    # visible in `trace summary` / `top` before it wastes its budget.
    # Callers pass their own ConvergenceTracker to tune the stall policy
    # (and to read the verdict back after the solve).
    if tracker is None:
        tracker = ConvergenceTracker(op)
    while it < total_iters:
        k = min(every, total_iters - it)
        # snapshot before the chunk: step programs may donate (delete)
        # their input buffers, so this host copy is the only pre-chunk
        # state the convergence residual can be measured against
        prev = state_snapshot(state)
        t0 = time.perf_counter()
        try:
            maybe_oom(chunk_op)
            with span("checkpoint.chunk", op=op, start=it, iters=k):
                new_state = maybe_poison(op, step_fn(state, k))
        except Exception as e:  # noqa: BLE001 — classify, then decide
            if classify_failure(e) is not FailureKind.RESOURCE or k <= 1:
                raise
            every = max(1, k // 2)
            metrics.counter("admission.chunk_shrunk").inc()
            record_event("chunk-shrunk", op=op, from_size=k, to_size=every,
                         reason=type(e).__name__)
            # the failed chunk may have consumed its (donated) input
            # buffers — restart the chunk from the last durable state
            loaded = load_checkpoint(path)
            if loaded is not None:
                it, arrays = loaded
                state = _unflatten_state(arrays)
            continue
        if guard is not None and not guard(new_state):
            record_event("numeric-abort", op=op, step=it + k,
                         retries=retries)
            if retries >= max_retries:
                raise NonFiniteError(
                    f"{op}: non-finite state at step {it + k} "
                    f"(after {retries} rollback retries)")
            retries += 1
            loaded = load_checkpoint(path)
            if loaded is None:
                raise NonFiniteError(
                    f"{op}: non-finite state at step {it + k} and no good "
                    f"checkpoint to roll back to")
            it, arrays = loaded
            state = _unflatten_state(arrays)
            metrics.counter("checkpoint.rollbacks").inc()
            record_event("checkpoint-rollback", op=op, resumed_step=it,
                         retries=retries)
            continue
        progress_from_states(tracker, it + k, prev, new_state, k,
                             time.perf_counter() - t0)
        state = new_state
        it += k
        with span("checkpoint.save", op=op, step=it):
            save_state_checkpoint(path, it, state)
    return state
