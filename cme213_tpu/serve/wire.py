"""v2 binary wire format: zero-copy frames for the serving transport.

PR 15's wire protocol shipped every request as 4-byte-length JSON with
numpy payloads as **base64** strings — three full copies of every array
(tobytes, b64encode, json.dumps) on each side of the wire.  At serving
rates the router burns more CPU en/decoding than the device spends
solving, which is the same disease the paper's kernels treat on-chip
(hw4's transpose staging through shared memory instead of strided
global loads; hw5's derived datatypes handing MPI the halo *in place*
instead of packing it).  This module is the transport-layer analog:
arrays travel as raw bytes straight off ``ndarray.data``, never through
an intermediate string.

**Frame layout** (all integers big-endian)::

    header   ">4sBBQII"   magic  version  ftype  rid  nsections  meta_len
    meta     meta_len bytes of UTF-8 JSON (control fields, op, tenant,
             timings — everything *small*; arrays never ride here)
    section  x nsections:
      desc   ">BBHQ"      dtype_len  ndim  flags  nbytes
      dtype  dtype_len ascii bytes (numpy ``dtype.str``: '<f8', '>i4',
             '|u1' — byte order always explicit, unlike ``str(dtype)``)
      shape  ndim x ">q"  (signed 8-byte dims: >2 GiB-safe, 0-d = no dims)
      bytes  nbytes raw C-contiguous array bytes

The first header byte (0xC3) can never begin a v1 frame — a v1 length
prefix of 0xC3xxxxxx would announce a >3 GiB JSON body — so a server
can peek 4 bytes and dispatch either protocol on the same port.  Arrays
inside a meta document are ``{"__sec__": i}`` references into the
frame's section table; the v1 ``{"__nd__": [dtype, shape, b64]}``
triple is still decoded for compatibility, so a v2 server accepts v1
payload documents unchanged.

**Span context on the wire.**  Request metas (v2) and request docs (v1)
carry two optional tracing fields: ``trace_id`` — the cross-process
trace the request belongs to — and ``parent_span`` — the sender's open
``serve.hop.*`` span id, which the receiving tier parents its own hop
under, so one request renders as one tree across client, front tier,
and replica (``trace waterfall``).  Response metas carry the
symmetrical extra ``hops`` — the front tier's per-hop residency
breakdown (wait/dispatch/requeue ms + requeue count) — which rides the
extras path below and lands on the client's result as ``res.hops``.

Write side: :func:`pack_frame` returns a *buffer list* (header bytes,
meta bytes, then alternating descriptors and live ``memoryview``s of
the arrays) pushed through ``socket.sendmsg`` by :func:`send_buffers` —
vectored I/O, no join, no copy.  Read side: :func:`read_frame_rest`
allocates each destination with ``np.empty(shape, dtype)`` and
``recv_into``s the payload directly into it.  :func:`parse_frame`
decodes the same layout from an in-memory buffer (the shared-memory
lane's slots, codec benches).
"""

from __future__ import annotations

import base64
import functools
import json
import socket
import struct

import numpy as np

#: first byte 0xC3 is unreachable as a v1 length prefix (see module doc)
MAGIC = b"\xc3WR2"
VERSION = 2

#: frame types
FT_REQUEST = 1        # op request; payload doc in meta, arrays in sections
FT_RESPONSE = 2       # SolveResult doc in meta, value arrays in sections
FT_CONTROL = 3        # ping / stats / hello / shm-setup / shm-ack
FT_CONTROL_REPLY = 4
FT_SHM = 5            # doorbell: the real frame lives in a shm ring slot

_HEAD = struct.Struct(">4sBBQII")   # magic, version, ftype, rid, nsec, meta_len
_SECT = struct.Struct(">BBHQ")      # dtype_len, ndim, flags, nbytes
_DIM = struct.Struct(">q")

HEAD_SIZE = _HEAD.size

#: sanity bounds a frame reader enforces before allocating anything
MAX_META_BYTES = 64 << 20
MAX_SECTIONS = 4096
MAX_NDIM = 32


class WireError(ConnectionError):
    """A malformed v2 frame (bad magic/version/bounds)."""


# ------------------------------------------------------------ raw I/O

def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Exactly ``n`` bytes or raise — EOF here is always mid-frame."""
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("EOF mid-frame")
        buf += chunk
    return buf


def recv_into_exact(sock: socket.socket, mv: memoryview) -> None:
    """Fill a writable byte view straight off the socket (no staging
    buffer — this is the zero-copy read half)."""
    got, n = 0, len(mv)
    while got < n:
        r = sock.recv_into(mv[got:], n - got)
        if r == 0:
            raise ConnectionError("EOF mid-frame")
        got += r


class BufReader:
    """Buffered frame reader over a socket: one ``recv`` pulls up to
    ``bufsize`` bytes and the many small exact reads a frame header
    needs (magic, head, meta, section descriptors) are served from the
    buffer — at serving rates the unbuffered path costs ~6 syscalls per
    frame, which is most of a pipelined request's CPU.  Large payload
    reads drain the buffer first, then ``recv_into`` the remainder
    straight into the destination array, so the zero-copy section path
    is preserved."""

    __slots__ = ("sock", "bufsize", "_buf", "_view", "_pos", "_end")

    def __init__(self, sock: socket.socket, bufsize: int = 1 << 16):
        self.sock = sock
        self.bufsize = bufsize
        self._buf = bytearray(bufsize)
        self._view = memoryview(self._buf)
        self._pos = 0
        self._end = 0

    def _fill(self) -> int:
        """One recv into the (empty) buffer; returns bytes read."""
        n = self.sock.recv_into(self._buf, self.bufsize)
        self._pos, self._end = 0, n
        return n

    def pending(self) -> int:
        """Bytes already buffered (0 means the next read may block —
        the moment to flush any batched writes)."""
        return self._end - self._pos

    def first4(self) -> bytes | None:
        """The 4 protocol-sniff bytes, or None on a clean EOF at a
        frame boundary."""
        if self._pos == self._end and self._fill() == 0:
            return None
        try:
            return self.recv_exact(4)
        except ConnectionError:
            return None

    def recv_exact(self, n: int) -> bytes:
        """Exactly ``n`` bytes or raise — EOF here is always mid-frame."""
        pos, end = self._pos, self._end
        if end - pos >= n:              # the hot path: already buffered
            self._pos = pos + n
            return bytes(self._buf[pos:pos + n])
        out = bytearray(self._buf[pos:end])
        self._pos = self._end = 0
        while len(out) < n:
            if n - len(out) >= self.bufsize:
                chunk = self.sock.recv(n - len(out))
                if not chunk:
                    raise ConnectionError("EOF mid-frame")
                out += chunk
            else:
                if self._fill() == 0:
                    raise ConnectionError("EOF mid-frame")
                take = min(n - len(out), self._end)
                out += self._buf[:take]
                self._pos = take
        return bytes(out)

    def recv_view(self, n: int):
        """A zero-copy view of the next ``n`` bytes when they are
        already buffered (valid until the next read), else the bytes
        from :meth:`recv_exact` — either way something ``struct`` can
        unpack without a staging copy on the hot path."""
        pos = self._pos
        if self._end - pos >= n:
            self._pos = pos + n
            return self._view[pos:pos + n]
        return self.recv_exact(n)

    def recv_into(self, mv: memoryview) -> None:
        """Fill a writable byte view: buffered bytes first, then
        ``recv_into`` the remainder directly (no staging copy)."""
        n = len(mv)
        have = min(n, self._end - self._pos)
        if have:
            mv[:have] = self._buf[self._pos:self._pos + have]
            self._pos += have
        got = have
        while got < n:
            r = self.sock.recv_into(mv[got:], n - got)
            if r == 0:
                raise ConnectionError("EOF mid-frame")
            got += r


def _src_exact(src, n: int) -> bytes:
    """Exact read off either a plain socket or a :class:`BufReader`."""
    return src.recv_exact(n) if isinstance(src, BufReader) \
        else recv_exact(src, n)


def send_buffers(sock: socket.socket, bufs: list) -> int:
    """Vectored write of a buffer list (``sendmsg``), looping on partial
    sends; falls back to one join+sendall where sendmsg is missing.
    Returns total bytes written."""
    total = 0
    if not hasattr(sock, "sendmsg"):    # pragma: no cover - non-POSIX
        blob = b"".join(bytes(b) for b in bufs)
        sock.sendall(blob)
        return len(blob)
    views = [b if isinstance(b, memoryview) else memoryview(b)
             for b in bufs]
    views = [v for v in views if len(v)]
    while views:
        sent = sock.sendmsg(views[:512])    # stay under IOV_MAX
        total += sent
        while sent:
            if sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0
    return total


# ------------------------------------------------------------ sections

def section_view(arr) -> tuple[str, tuple, np.ndarray]:
    """(dtype.str, caller shape, C-contiguous array) for one payload
    array.  The shape is captured *before* ``ascontiguousarray``, which
    promotes 0-d to (1,); ``dtype.str`` keeps byte order explicit."""
    a = np.asarray(arr)
    shape = a.shape
    a = np.ascontiguousarray(a)
    return a.dtype.str, shape, a


def _byte_view(a: np.ndarray) -> memoryview:
    # reshape(-1) is a free view on a C-contiguous array and turns 0-d
    # into (1,), which memoryview.cast('B') requires
    return memoryview(a.reshape(-1)).cast("B")


def pack_frame(ftype: int, rid: int, meta: dict,
               sections: list | tuple = ()) -> list:
    """Encode one frame as a buffer list for :func:`send_buffers`.
    ``sections`` are arrays (or anything ``np.asarray`` takes); their
    bytes ride as live memoryviews — nothing is copied here."""
    meta_b = json.dumps(meta).encode("utf-8")
    bufs = [None, meta_b]
    for arr in sections:
        dt, shape, a = section_view(arr)
        d = dt.encode("ascii")
        desc = (_SECT.pack(len(d), len(shape), 0, a.nbytes) + d
                + b"".join(_DIM.pack(s) for s in shape))
        bufs.append(desc)
        if a.nbytes:
            bufs.append(_byte_view(a))
    bufs[0] = _HEAD.pack(MAGIC, VERSION, ftype, rid, len(sections),
                         len(meta_b))
    return bufs


def frame_nbytes(bufs: list) -> int:
    return sum(len(b) if isinstance(b, (bytes, memoryview)) else
               memoryview(b).nbytes for b in bufs)


def frame_bytes(ftype: int, rid: int, meta: dict,
                sections: list | tuple = ()) -> bytes:
    """One contiguous blob of the frame (shm slots, codec benches)."""
    return b"".join(bytes(b) for b in
                    pack_frame(ftype, rid, meta, sections))


def send_frame_v2(sock: socket.socket, ftype: int, rid: int, meta: dict,
                  sections: list | tuple = ()) -> int:
    return send_buffers(sock, pack_frame(ftype, rid, meta, sections))


def _check_head(head: bytes) -> tuple[int, int, int, int]:
    magic, ver, ftype, rid, nsec, meta_len = _HEAD.unpack(head)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if ver != VERSION:
        raise WireError(f"unsupported wire version {ver}")
    if meta_len > MAX_META_BYTES or nsec > MAX_SECTIONS:
        raise WireError(f"frame bounds exceeded (meta={meta_len}, "
                        f"sections={nsec})")
    return ftype, rid, nsec, meta_len


def _check_sect(desc: bytes) -> tuple[int, int, int]:
    dlen, ndim, _flags, nbytes = _SECT.unpack(desc)
    if ndim > MAX_NDIM:
        raise WireError(f"section ndim {ndim} exceeds {MAX_NDIM}")
    return dlen, ndim, nbytes


def read_frame_rest(src, first4: bytes) -> tuple[int, int, dict, list]:
    """Finish reading a v2 frame whose first 4 bytes (the magic) were
    already consumed by protocol sniffing.  ``src`` is a socket or a
    :class:`BufReader`.  Returns ``(ftype, rid, meta, sections)`` with
    each section read straight into a freshly allocated array — one
    copy total, off the kernel buffer."""
    buffered = isinstance(src, BufReader)
    if buffered:
        # struct pieces come as zero-copy views into the read buffer;
        # only the json meta needs materialized bytes
        exact, view = src.recv_exact, src.recv_view
    else:
        exact = view = functools.partial(recv_exact, src)
    ftype, rid, nsec, meta_len = _check_head(first4
                                             + exact(HEAD_SIZE - 4))
    meta = json.loads(exact(meta_len)) if meta_len else {}
    sections = []
    for _ in range(nsec):
        dlen, ndim, nbytes = _check_sect(view(_SECT.size))
        dt = bytes(view(dlen)).decode("ascii")
        shape = struct.unpack(f">{ndim}q", view(ndim * 8))
        out = np.empty(shape, dtype=np.dtype(dt))
        if out.nbytes != nbytes:
            raise WireError(f"section length {nbytes} != "
                            f"{out.nbytes} for {dt}{shape}")
        if nbytes:
            if buffered:
                src.recv_into(_byte_view(out))
            else:
                recv_into_exact(src, _byte_view(out))
        sections.append(out)
    return ftype, rid, meta, sections


def parse_frame(buf) -> tuple[int, int, dict, list]:
    """Decode one frame from an in-memory buffer (a shm slot or a
    joined blob).  Arrays are **copied** out — the buffer is reusable
    the moment this returns."""
    mv = memoryview(buf)
    ftype, rid, nsec, meta_len = _check_head(bytes(mv[:HEAD_SIZE]))
    o = HEAD_SIZE
    meta = json.loads(bytes(mv[o:o + meta_len])) if meta_len else {}
    o += meta_len
    sections = []
    for _ in range(nsec):
        dlen, ndim, nbytes = _check_sect(bytes(mv[o:o + _SECT.size]))
        o += _SECT.size
        dt = bytes(mv[o:o + dlen]).decode("ascii")
        o += dlen
        shape = tuple(_DIM.unpack(bytes(mv[o + i * 8:o + i * 8 + 8]))[0]
                      for i in range(ndim))
        o += ndim * 8
        arr = np.frombuffer(mv[o:o + nbytes],
                            dtype=np.dtype(dt)).reshape(shape).copy()
        o += nbytes
        sections.append(arr)
    return ftype, rid, meta, sections


# ------------------------------------------------------ document codecs
#
# The value/payload/result codecs are shared between protocols via a
# pluggable array encoder ``nd(arr) -> doc``: v1 passes the base64
# triple encoder, v2 passes a SectionWriter that appends the array to
# the frame's section table and returns a {"__sec__": i} reference.
# Decoding accepts *both* spellings regardless of which protocol
# carried the document — that is the whole v1-compat story.

def nd_b64(arr) -> dict:
    """v1 array encoding: base64 triple (kept for legacy clients)."""
    dt, shape, a = section_view(arr)
    return {"__nd__": [dt, list(shape),
                       base64.b64encode(a.tobytes()).decode("ascii")]}


def nd_b64_decode(doc: dict) -> np.ndarray:
    dtype, shape, data = doc["__nd__"]
    return np.frombuffer(base64.b64decode(data),
                         dtype=np.dtype(dtype)).reshape(shape).copy()


class SectionWriter:
    """v2 array encoder: collects arrays into a frame section table."""

    def __init__(self):
        self.arrays: list = []

    def __call__(self, arr) -> dict:
        self.arrays.append(np.asarray(arr))
        return {"__sec__": len(self.arrays) - 1}


def encode_value(value, nd):
    """Wire-encode a result value: arrays via ``nd``, containers
    recurse, scalars pass through."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, np.ndarray):
        return nd(value)
    if isinstance(value, (np.generic,)):
        return nd(np.asarray(value))
    if isinstance(value, (list, tuple)):
        return {"__seq__": [encode_value(v, nd) for v in value]}
    if isinstance(value, dict):
        return {"__map__": {str(k): encode_value(v, nd)
                            for k, v in value.items()}}
    if hasattr(value, "__array__"):     # jax.Array et al.
        return nd(np.asarray(value))
    return {"__repr__": repr(value)}


def decode_value(doc, sections=None):
    """Inverse of :func:`encode_value`; understands both the v1
    ``__nd__`` base64 triple and the v2 ``__sec__`` section ref."""
    if isinstance(doc, dict):
        if "__sec__" in doc:
            if sections is None:
                raise WireError("__sec__ ref outside a sectioned frame")
            return sections[doc["__sec__"]]
        if "__nd__" in doc:
            return nd_b64_decode(doc)
        if "__seq__" in doc:
            return [decode_value(v, sections) for v in doc["__seq__"]]
        if "__map__" in doc:
            return {k: decode_value(v, sections)
                    for k, v in doc["__map__"].items()}
        if "__repr__" in doc:
            return doc["__repr__"]
    return doc


def encode_payload(op: str, payload, nd) -> dict:
    """Per-op payload serialization; ops are the
    ``serve.workloads.ADAPTERS`` keys."""
    if op == "spmv_scan":
        return {"a": nd(payload.a), "s": nd(payload.s),
                "k": nd(payload.k), "x": nd(payload.x),
                "iters": int(payload.iters)}
    if op == "heat":
        return {k: getattr(payload, k)
                for k in ("nx", "ny", "lx", "ly", "alpha", "iters",
                          "order", "ic", "bc_top", "bc_left",
                          "bc_bottom", "bc_right")}
    if op == "cipher":
        return {"text": nd(payload.text), "shift": int(payload.shift)}
    if op == "sort":
        return {"keys": nd(payload)}
    if op == "stub":
        return {"x": nd(payload)}
    raise ValueError(f"no wire codec for op {op!r}")


def decode_payload(op: str, doc: dict, sections=None):
    if op == "spmv_scan":
        from ..apps.spmv_scan import Problem

        return Problem(a=decode_value(doc["a"], sections),
                       s=decode_value(doc["s"], sections),
                       k=decode_value(doc["k"], sections),
                       x=decode_value(doc["x"], sections),
                       iters=int(doc["iters"]))
    if op == "heat":
        from ..config import SimParams

        return SimParams(**{k: doc[k] for k in doc})
    if op == "cipher":
        from .workloads import CipherRequest

        return CipherRequest(text=decode_value(doc["text"], sections),
                             shift=int(doc["shift"]))
    if op == "sort":
        return decode_value(doc["keys"], sections)
    if op == "stub":
        return decode_value(doc["x"], sections)
    raise ValueError(f"no wire codec for op {op!r}")


RESULT_FIELDS = ("rid", "op", "status", "reason", "rung", "shape_class",
                 "latency_ms", "batch_size", "degraded", "tenant",
                 "timing", "trace_id")


def encode_result(res, nd, **extra) -> dict:
    doc = {f: getattr(res, f) for f in RESULT_FIELDS}
    doc["value"] = encode_value(res.value, nd)
    doc.update(extra)
    return doc


_RESULT_SKIP = frozenset(RESULT_FIELDS) | {"value"}


def decode_result(doc: dict, sections=None):
    from .request import SolveResult

    res = SolveResult(
        **{f: doc.get(f) for f in RESULT_FIELDS},
        value=decode_value(doc.get("value"), sections))
    # transport-level extras (e.g. which fleet replica served it) ride
    # as plain attributes; consumers use getattr(res, "replica", None)
    for k, v in doc.items():
        if k not in _RESULT_SKIP:
            setattr(res, k, v)
    return res


def inline_sections(doc, sections):
    """Rewrite a v2 document's ``__sec__`` refs as v1 ``__nd__``
    triples — the downgrade path at a mixed-protocol edge (a v2 replica
    answering a v1 client through the fleet front end)."""
    if isinstance(doc, dict):
        if "__sec__" in doc:
            return nd_b64(sections[doc["__sec__"]])
        return {k: inline_sections(v, sections) for k, v in doc.items()}
    if isinstance(doc, list):
        return [inline_sections(v, sections) for v in doc]
    return doc
