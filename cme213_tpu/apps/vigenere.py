"""Vigenère cipher workload — create + statistical crack (reference hw3).

TPU-native redesign of the Thrust pipelines in
``hw/hw3/programming/create_cipher.cu`` and ``solve_cipher.cu``:

- text sanitization (``remove_copy_if`` over an ``upper_to_lower`` transform
  iterator, ``create_cipher.cu:31-50,111-113``) becomes mask → exclusive scan
  → scatter stream compaction (which is exactly how Thrust implements
  ``remove_copy_if`` internally — here it's explicit, fused by XLA);
- Vigenère encode/decode are the elementwise ops in ``ops/elementwise.py``;
- the letter histogram is the sort + ``upper_bound`` formulation
  (``solve_cipher.cu:131-154``) from ``ops/histogram.py``;
- the key-length detector computes the index of coincidence by
  autocorrelation (``inner_product(text, text<<i)``, threshold 1.6, spike
  confirmed at 2·k — ``solve_cipher.cu:187-208``) with a *fixed-shape*
  roll+mask comparison so one compiled function serves every lag;
- the per-coset frequency attack (``solve_cipher.cu:214-248``) runs all
  ``keyLength`` cosets in ONE batched op: the text reshaped to
  ``(rows, keyLength)`` gives each coset a column; per-column histograms are
  a single one-hot reduction; ``shift = argmax − ('e'−'a')``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.elementwise import vigenere_shift, vigenere_unshift
from ..ops.scan import exclusive_scan

_A = ord("a")
_E_MINUS_A = ord("e") - ord("a")


# ---------------------------------------------------------------- sanitize

@jax.jit
def _sanitize_device(raw: jnp.ndarray):
    """Lowercase + keep-mask + scatter compaction (all on device).

    Rejected bytes scatter into a sacrificial slot one past the end of an
    (n+1)-wide buffer, so they can never collide with a kept byte; the
    caller slices the compacted prefix.  One fused pass — the analog of
    the reference's single ``remove_copy_if`` over a ``transform_iterator``
    (create_cipher.cu:111-113).
    """
    n = raw.shape[0]
    # upper_to_lower: 'A'-'Z' -> 'a'-'z' (create_cipher.cu:31-38)
    is_upper = (raw >= ord("A")) & (raw <= ord("Z"))
    low = jnp.where(is_upper, raw + (ord("a") - ord("A")), raw)
    keep = (low >= ord("a")) & (low <= ord("z"))
    pos = exclusive_scan(keep.astype(jnp.int32))
    out = jnp.zeros(n + 1, dtype=low.dtype)
    out = out.at[jnp.where(keep, pos, n)].set(jnp.where(keep, low, 0))
    count = pos[-1] + keep[-1].astype(jnp.int32)
    return out[:-1], count


def sanitize(raw: np.ndarray) -> np.ndarray:
    """Uppercase→lowercase, strip everything but a-z (create_cipher.cu
    sanitizer).  Returns the compacted uint8 array."""
    raw = np.asarray(raw, dtype=np.uint8)
    out, count = _sanitize_device(jnp.asarray(raw))
    return np.array(out[: int(count)])


# ---------------------------------------------------------------- key gen

def generate_key(period: int, seed: int = 123) -> np.ndarray:
    """Period-length shift vector in [1, 26], via a minstd LCG — the engine
    the reference uses (``thrust::minstd_rand`` + ``uniform_int_distribution
    (1,26)``, create_cipher.cu:121-130)."""
    state = seed % 2147483647 or 1
    shifts = []
    for _ in range(period):
        state = (16807 * state) % 2147483647
        shifts.append(1 + state % 26)
    return np.asarray(shifts, dtype=np.int32)


def encode(text: np.ndarray, shifts: np.ndarray) -> np.ndarray:
    return np.asarray(vigenere_shift(jnp.asarray(text), jnp.asarray(shifts)))


def decode(text: np.ndarray, shifts: np.ndarray) -> np.ndarray:
    return np.asarray(vigenere_unshift(jnp.asarray(text), jnp.asarray(shifts)))


# ---------------------------------------------------------------- analytics

@jax.jit
def letter_histogram(text: jnp.ndarray) -> jnp.ndarray:
    """26-bin dense histogram via sort + searchsorted (solve_cipher.cu:
    131-154)."""
    data = jnp.sort(text)
    bounds = jnp.searchsorted(data, jnp.arange(_A, _A + 26, dtype=text.dtype),
                              side="right")
    lower = jnp.concatenate([jnp.zeros((1,), bounds.dtype), bounds[:-1]])
    return (bounds - lower).astype(jnp.int32)


@jax.jit
def digraph_top20(text: jnp.ndarray):
    """Top-20 letter bigrams of 26² counts (solve_cipher.cu:162-182).
    Returns (codes, counts); code = first·26 + second."""
    a = text[:-1].astype(jnp.int32) - _A
    b = text[1:].astype(jnp.int32) - _A
    codes = a * 26 + b
    counts = jax.ops.segment_sum(jnp.ones_like(codes), codes, num_segments=676)
    top_counts, top_codes = jax.lax.top_k(counts, 20)
    return top_codes, top_counts


@jax.jit
def _num_matches(text: jnp.ndarray, lag: jnp.ndarray) -> jnp.ndarray:
    """inner_product(text[:-lag], text[lag:], equal_to) with fixed shapes."""
    n = text.shape[0]
    shifted = jnp.roll(text, -lag)
    valid = jnp.arange(n) < (n - lag)
    return jnp.sum((text == shifted) & valid)


def index_of_coincidence(text: jnp.ndarray, lag: int) -> float:
    n = text.shape[0]
    matches = int(_num_matches(text, jnp.int32(lag)))
    return matches / ((n - lag) / 26.0)


@partial(jax.jit, static_argnames=("max_lag",))
def ioc_profile(text: jnp.ndarray, max_lag: int = 256) -> jnp.ndarray:
    """IOC for every lag in [1, max_lag) in ONE device call.

    The reference's detector loop does one ``inner_product`` per lag
    (solve_cipher.cu:187-208) — a host round trip each.  ``lax.map`` keeps
    the sweep on device (sequential, so memory stays O(n)) and returns the
    whole profile for host-side thresholding."""
    lags = jnp.arange(1, max_lag, dtype=jnp.int32)
    matches = jax.lax.map(lambda lag: _num_matches(text, lag), lags)
    n = text.shape[0]
    return matches.astype(jnp.float32) / ((n - lags).astype(jnp.float32)
                                          / 26.0)


def find_key_length(text: jnp.ndarray, threshold: float = 1.6,
                    max_lag: int = 256) -> int:
    """IOC autocorrelation detector (solve_cipher.cu:187-208): the first
    spike gives a candidate k; a spike at exactly 2k confirms it; any other
    spike is an unusual pattern.  Thresholding runs on the host over the
    device-computed profile, preserving the reference's exact scan order."""
    import numpy as np

    profile = np.asarray(ioc_profile(text, max_lag=max_lag))
    key_length = 0
    for lag in range(1, max_lag):
        if profile[lag - 1] > threshold:
            if key_length == 0:
                key_length = lag
            elif 2 * key_length == lag:
                return key_length
            else:
                raise ValueError("Unusual pattern in text!")
    raise ValueError("no key length found")


@partial(jax.jit, static_argnames=("key_length",))
def coset_shifts(text: jnp.ndarray, key_length: int) -> jnp.ndarray:
    """Frequency attack on all cosets at once (solve_cipher.cu:214-248).

    Pads the text to a row multiple, reshapes to (rows, key_length) so coset
    i is column i, builds per-column letter histograms in one one-hot
    reduction, and recovers ``shift = argmax − ('e'−'a') (mod 26)``.
    """
    n = text.shape[0]
    rows = -(-n // key_length)
    padded = jnp.zeros((rows * key_length,), text.dtype).at[:n].set(text)
    valid = (jnp.arange(rows * key_length) < n).reshape(rows, key_length)
    letters = (padded.astype(jnp.int32) - _A).reshape(rows, key_length)
    oh = jax.nn.one_hot(jnp.where(valid, letters, -1), 26, dtype=jnp.int32)
    hist = oh.sum(axis=0)                       # (key_length, 26)
    argmax = jnp.argmax(hist, axis=1)
    return (argmax - _E_MINUS_A) % 26


# ---------------------------------------------------------------- drivers

@dataclass
class CrackResult:
    key_length: int
    shifts: np.ndarray
    plain_text: np.ndarray


def crack(cipher_text: np.ndarray) -> CrackResult:
    """Full solve pipeline (solve_cipher.cu main): histogram/digraph stats are
    available via the functions above; the crack itself is IOC key-length
    detection + batched coset attack + decode."""
    dev = jnp.asarray(np.asarray(cipher_text, dtype=np.uint8))
    key_length = find_key_length(dev)
    shifts = np.asarray(coset_shifts(dev, key_length))
    plain = decode(np.asarray(cipher_text), shifts)
    return CrackResult(key_length, shifts, plain)


def create_cipher(raw_text: np.ndarray, period: int, seed: int = 123):
    """create_cipher.cu main: sanitize → key gen → encode.
    Returns (clean_text, shifts, cipher_text)."""
    clean = sanitize(raw_text)
    shifts = generate_key(period, seed)
    cipher = encode(clean, shifts)
    return clean, shifts, cipher


def print_letter_frequencies(text: jnp.ndarray) -> None:
    """Frequency-table printout in the reference's contractual format
    ("a: .03" per line + sum, solve_cipher.cu:142-154)."""
    hist = np.asarray(letter_histogram(text))
    n = text.shape[0]
    print(f"Text length: {n}\n")
    for i in range(26):
        print(f"{chr(_A + i)}: {hist[i] / n}")
    print(f"\nSum of histogram: {hist.sum() / n}\n")


def print_digraph_table(text: jnp.ndarray) -> None:
    """Top-20 bigram printout ("kh: .001" style, solve_cipher.cu:177-182)."""
    codes, counts = digraph_top20(text)
    codes, counts = np.asarray(codes), np.asarray(counts)
    total = text.shape[0] - 1
    for c, cnt in zip(codes, counts):
        print(f"{chr(_A + c // 26)}{chr(_A + c % 26)}:  {cnt / total}")


def key_string(shifts) -> str:
    """Printable key: shift s → letter chr(s mod 26 + 'a') (shift 26 ≡ 0
    prints 'a'); used identically by both CLIs so round-trips agree."""
    return "".join(chr((int(s) % 26) + _A) for s in shifts)


def main_create(argv, out_path: str = "cipher_text.txt"):
    """CLI of create_cipher.cu:77-99: ``input.txt period`` → writes
    ``cipher_text.txt``."""
    path, period = argv[1], int(argv[2])
    raw = np.fromfile(path, dtype=np.uint8)
    clean, shifts, cipher = create_cipher(raw, period)
    print("Key:", key_string(shifts))
    cipher.tofile(out_path)
    return 0


def main_solve(argv, out_path: str = "plain_text.txt"):
    """CLI of solve_cipher.cu:103-274: ``cipher_text.txt`` → stats tables,
    key, and ``plain_text.txt``."""
    cipher = np.fromfile(argv[1], dtype=np.uint8)
    dev = jnp.asarray(cipher)
    print_letter_frequencies(dev)
    print_digraph_table(dev)
    result = crack(cipher)
    print(f"\nkeyLength: {result.key_length}")
    print("\nKey:", key_string(result.shifts), "\n")
    result.plain_text.tofile(out_path)
    return 0


def main(argv) -> int:
    """Dispatch: ``vigenere [create] input.txt period`` encodes (the
    reference's create_cipher CLI shape), ``vigenere solve cipher.txt``
    cracks (solve_cipher's).  The bare form without the ``create`` word
    matches the reference binary exactly."""
    args = argv[1:]
    if args and args[0] in ("create", "solve"):
        sub, args = args[0], args[1:]
    else:
        sub = "create"
    if (sub == "create" and len(args) != 2) or (sub == "solve"
                                                and len(args) != 1):
        print("usage: vigenere [create] input.txt period\n"
              "       vigenere solve cipher_text.txt")
        return 2
    try:
        if sub == "solve":
            return main_solve(["solve", *args])
        return main_create(["create", *args])
    except (OSError, ValueError) as e:
        print(f"error: {e}")
        return 2


if __name__ == "__main__":
    import sys

    raise SystemExit(main(sys.argv))
