"""Fleet serving tier: wire codec bitwise round-trips, the socket
transport's two drive modes, tenant-fair routing (deficit round robin),
per-replica breakers, the zero-loss requeue ledger, and SLO-burn
autoscaling hysteresis — all deterministic (``VirtualClock`` for every
policy decision; sockets only where sockets are the thing under test).

The e2e replica-kill arc (worker processes, SIGKILL, flight-recorder
read-back) lives in the tier-1 fleet gate and in the ``slow``-marked
test at the bottom; everything else here runs in-process.
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

from cme213_tpu.core import faults, metrics, trace
from cme213_tpu.core.resilience import VirtualClock
from cme213_tpu.serve import OK, QUEUE_FULL, SHED, Server, SolveResult
from cme213_tpu.serve.loadgen import build_mix
from cme213_tpu.serve.router import ROUTE_OP, Autoscaler, Router
from cme213_tpu.serve.transport import (
    TransportClient,
    TransportServer,
    decode_payload,
    decode_result,
    decode_value,
    encode_payload,
    encode_result,
    encode_value,
    recv_frame,
    send_frame,
)
from cme213_tpu.serve.workloads import ADAPTERS


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.clear_events()
    metrics.reset()
    yield
    faults.reset()
    metrics.reset()


def _bits(value) -> bytes:
    return np.ascontiguousarray(np.asarray(value)).tobytes()


# ------------------------------------------------------------ wire codec

def test_frame_roundtrip_and_clean_eof():
    a, b = socket.socketpair()
    try:
        doc = {"op": "cipher", "tenant": "t0", "nested": {"k": [1, 2.5]}}
        send_frame(a, doc)
        assert recv_frame(b) == doc
        a.close()
        assert recv_frame(b) is None          # EOF at a frame boundary
    finally:
        b.close()


def test_nd_value_roundtrip_is_bitwise():
    rng = np.random.default_rng(7)
    for arr in (rng.standard_normal((5, 3)),
                rng.standard_normal(17).astype(np.float32),
                rng.integers(0, 255, 64).astype(np.uint8),
                np.array(3.14159, dtype=np.float64)):
        wire = json.loads(json.dumps(encode_value(arr)))
        back = decode_value(wire)
        assert back.dtype == arr.dtype and back.shape == arr.shape
        assert back.tobytes() == arr.tobytes()
    # containers recurse; scalars pass through
    doc = encode_value({"xs": [np.arange(4), 2, "s"], "ok": True})
    got = decode_value(json.loads(json.dumps(doc)))
    assert got["ok"] is True and got["xs"][1] == 2 and got["xs"][2] == "s"
    assert got["xs"][0].tobytes() == np.arange(4).tobytes()


def test_payload_codecs_roundtrip_every_op():
    specs = build_mix("spmv,heat,cipher", 6, seed=3)
    assert {s.op for s in specs} == {"spmv_scan", "heat", "cipher"}
    for spec in specs:
        wire = json.loads(json.dumps(encode_payload(spec.op, spec.payload)))
        back = decode_payload(spec.op, wire)
        if spec.op == "spmv_scan":
            for f in ("a", "s", "k", "x"):
                assert _bits(getattr(back, f)) == _bits(
                    getattr(spec.payload, f))
            assert back.iters == spec.payload.iters
        elif spec.op == "heat":
            for f in ("nx", "ny", "alpha", "iters", "order", "ic"):
                assert getattr(back, f) == getattr(spec.payload, f)
        else:
            assert _bits(back.text) == _bits(spec.payload.text)
            assert back.shift == spec.payload.shift


def test_payload_codec_rejects_unknown_op():
    with pytest.raises(ValueError, match="no wire codec"):
        encode_payload("spmv", None)   # mix name, not an adapter key


def test_result_roundtrip_keeps_fields_and_extras():
    res = SolveResult(rid=9, op="cipher", status=OK, reason=None,
                      rung="jit", shape_class="c64", latency_ms=1.25,
                      batch_size=3, degraded=False, tenant="t1",
                      timing={"queue_ms": 0.5}, trace_id="abc",
                      value=np.arange(6, dtype=np.uint8))
    doc = json.loads(json.dumps(encode_result(res, replica=2)))
    back = decode_result(doc)
    for f in ("rid", "op", "status", "rung", "shape_class", "latency_ms",
              "batch_size", "degraded", "tenant", "timing", "trace_id"):
        assert getattr(back, f) == getattr(res, f)
    assert back.value.tobytes() == res.value.tobytes()
    assert getattr(back, "replica") == 2   # transport extra rides along


# ------------------------------------------------------- drive modes

def _serve_serial(specs):
    """Reference values: each spec solved alone on a direct server."""
    server = Server(adapters=ADAPTERS, clock=VirtualClock())
    out = []
    for spec in specs:
        server.submit(spec.op, spec.payload, tenant=spec.tenant)
        out.extend(server.drain())
    return out


def test_transport_caller_drive_pump_delivers():
    server = Server(adapters=ADAPTERS, clock=VirtualClock(), max_batch=4)
    ts = TransportServer(server, drive="caller").start()
    try:
        spec = build_mix("cipher", 1, seed=5)[0]
        got = {}

        def client():
            with TransportClient(ts.addr) as c:
                got["res"] = c.solve(spec.op, spec.payload)

        t = threading.Thread(target=client, daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not len(server.queue):
            time.sleep(0.01)
        assert len(server.queue) == 1      # parked until the owner pumps
        ts.pump()
        t.join(10)
        assert not t.is_alive()
        res = got["res"]
        assert res.status == OK
        ref = _serve_serial([spec])[0]
        assert _bits(res.value) == _bits(ref.value)
    finally:
        ts.close()


def test_transport_caller_drive_sheds_at_the_door():
    server = Server(adapters=ADAPTERS, clock=VirtualClock(), capacity=1)
    ts = TransportServer(server, drive="caller").start()
    try:
        specs = build_mix("cipher", 2, seed=6)
        got = {}

        def first():
            with TransportClient(ts.addr) as c:
                got["first"] = c.solve(specs[0].op, specs[0].payload)

        t = threading.Thread(target=first, daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not len(server.queue):
            time.sleep(0.01)
        # queue full: the refusal comes back without any pumping
        with TransportClient(ts.addr) as c:
            shed = c.solve(specs[1].op, specs[1].payload)
        assert shed.status == SHED and shed.reason == QUEUE_FULL
        ts.pump()
        t.join(10)
        assert got["first"].status == OK
    finally:
        ts.close()


def test_transport_thread_drive_concurrent_clients_bitwise():
    server = Server(adapters=ADAPTERS, clock=VirtualClock(), max_batch=4)
    ts = TransportServer(server, drive="thread").start()
    try:
        specs = build_mix("cipher", 8, seed=11, tenants=2)
        results = [None] * len(specs)

        def client(i, spec):
            with TransportClient(ts.addr) as c:
                results[i] = c.solve(spec.op, spec.payload,
                                     tenant=spec.tenant)

        threads = [threading.Thread(target=client, args=(i, s), daemon=True)
                   for i, s in enumerate(specs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert all(r is not None and r.status == OK for r in results)
        refs = _serve_serial(specs)
        for res, ref in zip(results, refs):
            assert _bits(res.value) == _bits(ref.value)
        with TransportClient(ts.addr) as c:
            assert c.control("ping")["ok"] is True
            stats = c.control("stats")
            assert stats["ok"] and stats["stats"]["queue_depth"] == 0
            assert stats["stats"]["pending"] == 0
            assert stats["stats"]["batches"] >= 1
    finally:
        ts.close()


def test_transport_rejects_bad_drive_mode():
    server = Server(adapters=ADAPTERS, clock=VirtualClock())
    with pytest.raises(ValueError, match="drive must be"):
        TransportServer(server, drive="psychic")


# --------------------------------------------------------- router: DRR

def _doc(tenant="default", op="cipher"):
    return {"op": op, "tenant": tenant, "payload": {}}


def test_drr_noisy_tenant_cannot_starve_quiet():
    router = Router(clock=VirtualClock())
    router.register_replica(0, capacity=1)
    for _ in range(40):
        assert router.submit(_doc("noisy")) is not None
    quiet = [router.submit(_doc("quiet")) for _ in range(4)]
    assert all(q is not None for q in quiet)

    order = []
    while True:
        picked = router.next_assignment()
        if picked is None:
            break
        ticket, rank = picked
        order.append(ticket.tenant)
        router.complete(ticket, rank)
    assert len(order) == 44
    # DRR interleaves every round: all 4 quiet dispatches land within the
    # first handful of picks despite 40 noisy requests queued ahead
    quiet_positions = [i for i, t in enumerate(order) if t == "quiet"]
    assert len(quiet_positions) == 4
    assert max(quiet_positions) < 10
    ev = trace.events("request-routed")
    assert len(ev) == 44 and all(e["replica"] == 0 for e in ev)


def test_drr_weights_bias_dispatch_share():
    # weight 0.5 earns a dispatch credit every *other* visit, so the
    # best-effort tenant gets half the gold tenant's share while both
    # backlogs stay non-empty
    router = Router(clock=VirtualClock(), weights={"best-effort": 0.5})
    router.register_replica(0, capacity=1)
    for _ in range(30):
        router.submit(_doc("gold"))
        router.submit(_doc("best-effort"))
    order = []
    for _ in range(18):
        ticket, rank = router.next_assignment()
        order.append(ticket.tenant)
        router.complete(ticket, rank)
    assert order.count("gold") == 2 * order.count("best-effort")


def test_router_sheds_when_backlog_full():
    router = Router(clock=VirtualClock(), capacity=2)
    assert router.submit(_doc()) is not None
    assert router.submit(_doc()) is not None
    assert router.submit(_doc()) is None
    assert metrics.counter("fleet.shed.queue-full").value == 1
    assert router.backlog() == 2


# ----------------------------------------------- router: breakers + loss

def test_breaker_opens_and_routes_around_bad_replica():
    clock = VirtualClock()
    router = Router(clock=clock, breaker_threshold=2, breaker_cooldown_s=5.0)
    router.register_replica(0, capacity=4)
    router.register_replica(1, capacity=4)
    router.submit(_doc())

    # rank 0 wins ties; fail it at the socket twice -> breaker opens
    for _ in range(2):
        ticket, rank = router.next_assignment()
        assert rank == 0
        router.fail_transport(ticket, rank)
    assert router.state()["replicas"]["r0"]["breaker"] == "open"
    ticket, rank = router.next_assignment()
    assert rank == 1                       # routed around the open breaker
    assert ticket.requeues == 2
    router.complete(ticket, rank)
    assert router.total_requeues == 2 and router.requeues[0] == 2

    # cooldown elapses: the half-open probe readmits rank 0
    clock.advance(6.0)
    router.submit(_doc())
    _, rank = router.next_assignment()
    assert rank == 0


def test_mark_down_requeues_inflight_at_front_zero_loss():
    router = Router(clock=VirtualClock())
    router.register_replica(0, capacity=4)
    t_old = router.submit(_doc("a"))
    t_new = router.submit(_doc("a"))
    assigned = [router.next_assignment() for _ in range(2)]
    assert all(a is not None and a[1] == 0 for a in assigned)
    assert router.inflight() == 2

    lost = router.mark_down(0, reason="sigkill")
    assert {t.seq for t in lost} == {t_old.seq, t_new.seq}
    assert router.inflight() == 0 and router.backlog() == 2
    ev = trace.events("request-requeued")
    assert len(ev) == 2 and all(e["from_replica"] == 0 for e in ev)
    # a completion racing the death is recognized as stale
    assert router.complete(t_old, 0) is False

    router.register_replica(1, capacity=4)
    redone = [router.next_assignment() for _ in range(2)]
    assert {a[0].seq for a in redone} == {t_old.seq, t_new.seq}
    assert all(a[1] == 1 for a in redone)
    assert all(a[0].requeues == 1 for a in redone)


# ------------------------------------------------- autoscaler hysteresis

def _scaler(clock, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 2)
    kw.setdefault("burn_sustain_s", 3.0)
    kw.setdefault("ok_sustain_s", 6.0)
    kw.setdefault("cooldown_s", 10.0)
    return Autoscaler(clock=clock, **kw)


def test_autoscaler_burn_must_sustain_before_scale_up():
    clock = VirtualClock()
    a = _scaler(clock)
    assert a.evaluate(True, 0.9, 1) is None       # burn just started
    clock.advance(2.0)
    assert a.evaluate(True, 0.9, 1) is None       # 2s < burn_sustain_s
    clock.advance(1.5)
    assert a.evaluate(True, 0.9, 1) == "up"       # sustained
    ev = trace.events("scale-up")
    assert ev[-1]["replicas"] == 2 and ev[-1]["reason"] == "slo-burn"
    # the burn window restarts after an action: still burning right
    # after the scale-up is not an immediate second action
    clock.advance(4.0)
    assert a.evaluate(True, 0.9, 2) is None


def test_autoscaler_scale_down_needs_health_idle_and_cooldown():
    clock = VirtualClock()
    a = _scaler(clock)
    assert a.evaluate(True, 0.9, 1) is None       # burn starts at t=0
    clock.advance(3.0)
    assert a.evaluate(True, 0.9, 1) == "up"       # action at t=3
    assert a.evaluate(False, 0.1, 2) is None      # ok timer starts (t=3)
    clock.advance(6.0)                            # t=9: ok sustained, but
    assert a.evaluate(False, 0.1, 2) is None      # cooldown (9-3 < 10)
    clock.advance(4.5)                            # t=13.5: cooled
    assert a.evaluate(False, 0.1, 2) == "down"
    ev = trace.events("scale-down")
    assert ev[-1]["replicas"] == 1 and ev[-1]["reason"] == "slo-ok"
    # at the floor, sustained health never shrinks below min_replicas
    clock.advance(20.0)
    assert a.evaluate(False, 0.0, 1) is None
    clock.advance(20.0)
    assert a.evaluate(False, 0.0, 1) is None


def test_autoscaler_busy_fleet_resets_the_idle_timer():
    clock = VirtualClock()
    a = _scaler(clock)
    assert a.evaluate(False, 0.1, 2) is None      # idle timer starts
    clock.advance(5.0)
    assert a.evaluate(False, 0.8, 2) is None      # busy: timer reset
    clock.advance(5.0)
    assert a.evaluate(False, 0.1, 2) is None      # restarted, not sustained
    clock.advance(6.0)
    assert a.evaluate(False, 0.1, 2) == "down"


def test_autoscaler_is_deterministic_under_virtual_clock():
    script = [(True, 0.9, 1), (True, 0.9, 1), (True, 0.9, 1),
              (False, 0.2, 2), (False, 0.2, 2), (False, 0.2, 2),
              (False, 0.2, 2), (False, 0.2, 2)]

    def run():
        clock = VirtualClock()
        a = _scaler(clock)
        out = []
        for burning, occ, n in script:
            out.append(a.evaluate(burning, occ, n))
            clock.advance(2.0)
        return out

    first, second = run(), run()
    assert first == second
    assert "up" in first and "down" in first


# ------------------------------------------- pipelined channel faults

class _MiniFleet:
    """Just enough fleet for a ReplicaChannel: the router lock, the
    completion hooks, and v1-style ticket delivery."""

    def __init__(self, router):
        self.router = router
        self._cv = threading.Condition()

    def _observe(self, meta):
        pass

    def _deliver(self, ticket, meta, sections=()):
        from cme213_tpu.serve import wire
        ticket.result = (wire.inline_sections(meta, list(sections))
                         if sections else meta)
        ticket.done.set()


def _v2_tickets(router, specs):
    from cme213_tpu.serve import wire
    tickets = []
    for spec in specs:
        sw = wire.SectionWriter()
        doc = {"op": spec.op,
               "payload": wire.encode_payload(spec.op, spec.payload, sw),
               "tenant": spec.tenant}
        t = router.submit(doc)
        assert t is not None
        t.sections = sw.arrays
        t.done = threading.Event()
        tickets.append(t)
    return tickets


def test_sever_with_eight_in_flight_requeues_all_via_ledger():
    """The pipelined-world replica-kill contract: ONE connection with 8
    requests in flight dies mid-pipeline; the channel fails all 8 back
    to the router's ledger (8 ``request-requeued``), and a healthy
    replica then serves every one bitwise-equal — zero accepted-request
    loss without a single request-level retry by the client."""
    from cme213_tpu.serve.fleet import ReplicaChannel

    router = Router(clock=VirtualClock())
    router.register_replica(0, capacity=8)
    fleet = _MiniFleet(router)
    specs = build_mix("cipher", 8, seed=17, tenants=2)
    tickets = _v2_tickets(router, specs)

    # replica 0 accepts but never steps: the whole window stays in flight
    server_a = Server(adapters=ADAPTERS, clock=VirtualClock(), max_batch=8)
    ts_a = TransportServer(server_a, drive="caller").start()
    chan = ReplicaChannel(fleet, 0, ts_a.addr, shm=False)
    try:
        sent = 0
        while True:
            a = router.next_assignment()
            if a is None:
                break
            ticket, rank = a
            assert rank == 0
            chan.send(ticket)
            sent += 1
        assert sent == 8 and router.inflight() == 8
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and len(server_a.queue) < 8:
            time.sleep(0.01)
        assert len(server_a.queue) == 8     # all 8 pipelined on one conn

        ts_a.close()                        # SIGKILL as seen from a socket
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and router.backlog() < 8:
            time.sleep(0.01)
        assert router.backlog() == 8 and router.inflight() == 0
        assert router.total_requeues == 8
        assert len(trace.events("request-requeued")) == 8
        assert all(t.requeues == 1 for t in tickets)
    finally:
        chan.close()
        ts_a.close()

    # a healthy replica drains the requeued window: nothing was lost
    router.mark_down(0, reason="severed")
    router.register_replica(1, capacity=8)
    server_b = Server(adapters=ADAPTERS, clock=VirtualClock(), max_batch=8)
    ts_b = TransportServer(server_b, drive="thread").start()
    chan_b = ReplicaChannel(fleet, 1, ts_b.addr, shm=False)
    try:
        while True:
            a = router.next_assignment()
            if a is None:
                break
            chan_b.send(a[0])
        for t in tickets:
            assert t.done.wait(30)
        results = [decode_result(t.result) for t in tickets]
        assert all(r.status == OK for r in results)
        assert all(getattr(r, "replica", None) == 1 for r in results)
        refs = _serve_serial(specs)
        for res, ref in zip(results, refs):
            assert _bits(res.value) == _bits(ref.value)
        assert router.inflight() == 0 and router.backlog() == 0
    finally:
        chan_b.close()
        ts_b.close()


# ----------------------------------------------------- fault grammar

def test_replica_kill_clause_parses_and_misses_other_ranks():
    plan = faults.FaultPlan.parse("replica-kill:1:3")
    (clause,) = plan.clauses
    assert clause.kind == "replica-kill" and clause.op == "1"
    assert clause.nth == 3
    with faults.injected("replica-kill:7"):
        faults.maybe_kill_replica()   # rank mismatch: must be a no-op
    with pytest.raises(faults.FaultSpecError):
        faults.FaultPlan.parse("replica-kill")


# --------------------------------------------------- e2e fleet kill arc

@pytest.mark.slow
def test_fleet_survives_replica_kill_with_zero_loss(monkeypatch):
    """Two worker processes, SIGKILL one mid-batch: every accepted
    request is still served, requeued results are bitwise-equal to a
    serial solve, and the dead replica relaunches at incarnation 1.
    The tier-1 fleet gate runs this same arc through the CLI."""
    from cme213_tpu.serve.fleet import Fleet

    monkeypatch.setenv("CME213_FAULTS", "replica-kill:1:1")
    fleet = Fleet(replicas=2, mix="cipher", warm_requests=2,
                  max_batch=4).start()
    try:
        specs = build_mix("cipher", 24, seed=21, tenants=2)
        results = [None] * len(specs)

        def client(i, spec):
            with TransportClient(fleet.addr) as c:
                results[i] = c.solve(spec.op, spec.payload,
                                     tenant=spec.tenant)

        threads = [threading.Thread(target=client, args=(i, s), daemon=True)
                   for i, s in enumerate(specs)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert all(r is not None for r in results)
        assert all(r.status == OK for r in results)
        refs = _serve_serial(specs)
        for res, ref in zip(results, refs):
            assert _bits(res.value) == _bits(ref.value)
        # the relaunch races the last response: wait for incarnation 1
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            stats = fleet.stats()
            r1 = stats["replicas"].get("r1", {})
            if r1.get("incarnation") == 1 and r1.get("up"):
                break
            time.sleep(0.25)
    finally:
        fleet.close()
    assert stats["requeues"] >= 1
    assert stats["replicas"]["r1"]["incarnation"] == 1
    assert stats["replicas"]["r1"]["up"] is True
    assert trace.events("request-requeued")
    served_by = {e["replica"] for e in trace.events("request-routed")}
    assert served_by == {0, 1}
