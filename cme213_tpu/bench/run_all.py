"""Run every sweep and write CSV artifacts (the L7 harness entry point).

Usage: ``python -m cme213_tpu.bench.run_all [--out DIR] [--quick]``

Failure handling: a sweep that raises is retried ONCE (a flaky cell —
transient backend error, injected ``CME213_FAULTS=fail:sweep.<name>`` —
must not zero a multi-hour capture run), and every failure, recovered or
final, lands in ``<out>/failures.json``::

    {"failed":  [{"sweep", "attempt", "error", "message"}, ...],
     "retried": [...]}   # first-attempt failures whose retry succeeded

The exit code stays meaningful to the capture layer (``tpu_capture.sh``
writes retryable ``.failed`` markers off it): 0 when every sweep
ultimately produced rows — even if some needed their retry — and 1 only
when a sweep failed both attempts.

Telemetry: each completed sweep emits a ``sweep-complete`` trace event
and its metrics-registry delta (demotions, served rungs, retries, span
histograms — ``core/metrics.py``) is attached to that sweep's row set in
``<out>/metrics.json``, keyed by sweep name.  The deltas ride in a
sidecar instead of extra CSV columns so the banked-CSV comparators and
the capture layer's shell parsers keep seeing the schema they pin.

Profiling: set ``CME213_PROFILE_DIR=/path`` to wrap the whole run in
``jax.profiler.trace`` (the XPlane kernel-level profile, viewable in
TensorBoard/Perfetto) and to drop a ``device_memory_profile`` snapshot
after each sweep — recorded as structured ``device-memory`` trace
events, so memory growth across sweeps is analyzable with the trace
CLI.  Profiling failures are warnings, never sweep failures.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None) -> int:
    from ..core import flight
    from . import sweeps

    flight.install()   # a crashed sweep leaves its black box behind
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="bench_results")
    ap.add_argument("--quick", action="store_true",
                    help="small sizes (CI/CPU-friendly)")
    ap.add_argument("--only", default=None,
                    help="comma-separated CSV basenames (without .csv) "
                         "to run, e.g. --only sort_threads,spmv_suite")
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    q = args.quick
    only = (set(t.strip() for t in args.only.split(",") if t.strip())
            if args.only else None)

    jobs = [
        ("data_bandwidth_vector_length.csv",
         lambda: sweeps.cipher_vector_length_sweep(
             steps=3 if q else 25, max_bytes=1 << 16 if q else 1 << 26)),
        ("bandwidth_vs_avg_edges.csv",
         lambda: sweeps.pagerank_avg_edges_sweep(
             num_nodes=1 << 12 if q else 1 << 21,
             edges_range=range(2, 5) if q else range(2, 21),
             iterations=4 if q else 20)),
        ("heat_bandwidth.csv",
         lambda: sweeps.heat_sweep(
             # 5 sizes x 3 orders: the reference table's shape
             # (hw/hw2/programming/data/data.ods measures 5 grid sizes)
             sizes=(64,) if q else (250, 500, 1000, 2000, 4000),
             orders=(2, 4, 8), iters=3 if q else 200)),
        ("pallas_tile.csv",
         lambda: sweeps.pallas_tile_sweep(
             size=32 if q else 2000, order=2 if q else 8,
             iters=2 if q else 100,
             tiles=(8, 16) if q else (40, 80, 200, 400))),
        ("heat_kernels.csv",
         lambda: sweeps.heat_kernel_sweep(
             size=64 if q else 4000, order=8, iters=8 if q else 64,
             ks=(2, 4) if q else (2, 4, 8))),
        ("pipeline_tune.csv",
         lambda: sweeps.pipeline_tune_sweep(
             size=64 if q else 4000, order=8, iters=4 if q else 64,
             ks=(1, 2) if q else (1, 2, 4, 8, 16),
             targets=(16,) if q else (256, 192, 128, 64))),
        ("transfer_bandwidth.csv",
         lambda: sweeps.transfer_bandwidth_sweep(
             sizes=(1 << 16,) if q else (1 << 20, 1 << 24, 1 << 27))),
        ("scan_bandwidth.csv",
         lambda: sweeps.scan_sweep(
             n=1 << 16 if q else 1 << 26,
             num_segments=1 << 8 if q else 1 << 16)),
        ("dist_heat_scaling.csv",
         lambda: sweeps.dist_heat_sweep(
             size=32 if q else 2000, order=2 if q else 8,
             iters=3 if q else 100,
             ndevs=(1, 2) if q else (1, 2, 4, 8),
             # tuned-kernel scheme only where it is a real timing (TPU,
             # compiled); interpreter rows live in the compile-coverage
             # artifact below, not in this timing table
             pallas=None)),
        ("dist_heat_compile_coverage.csv",
         lambda: sweeps.dist_heat_compile_coverage(
             size=32 if q else 2000, order=2 if q else 8,
             iters=2 if q else 4,
             ndevs=(1, 2) if q else (1, 2, 4, 8))),
        ("sort_threads.csv",
         lambda: sweeps.sort_thread_sweep(
             num_elements=20_000 if q else 16_000_000,
             threads=(1, 2) if q else (1, 2, 4, 8, 16, 32))),
        ("spmv_pallas_coverage.csv",
         lambda: sweeps.spmv_pallas_coverage(
             scale=0.002 if q else 1.0, iters=1)),
        ("spmv_suite.csv",
         lambda: sweeps.spmv_suite_sweep(
             scale=0.002 if q else 1.0,
             kernels=("flat",) if q else None)),
        ("spmv_scan_sweep.csv",
         lambda: sweeps.spmv_scan_sweep(
             ns=(1 << 12,) if q else (1 << 16, 1 << 20, 1 << 22),
             iters=2 if q else 8,
             kernels=("flat", "blocked") if q else None)),
        ("sort_sweep.csv",
         lambda: sweeps.sort_sweep(
             ns=(1 << 12,) if q else (1 << 16, 1 << 20))),
    ]
    if only is not None:
        known = {f[:-len(".csv")] for f, _ in jobs}
        unknown = only - known
        if unknown:
            print(f"--only: unknown sweep name(s) {sorted(unknown)}; "
                  f"choose from {sorted(known)}", file=sys.stderr)
            return 2
    from ..core import faults, metrics, trace

    profile_dir = os.environ.get("CME213_PROFILE_DIR")
    profiling = False
    if profile_dir:
        try:
            import jax

            os.makedirs(profile_dir, exist_ok=True)
            jax.profiler.start_trace(profile_dir)
            profiling = True
        except Exception as e:  # noqa: BLE001 — profiling is best-effort
            print(f"CME213_PROFILE_DIR: profiler unavailable "
                  f"({type(e).__name__}: {e})", file=sys.stderr)

    def _memory_snapshot(name: str) -> None:
        """Per-sweep device-memory pprof snapshot + structured event."""
        if not profiling:
            return
        try:
            import jax

            blob = jax.profiler.device_memory_profile()
            path = os.path.join(profile_dir, f"memory_{name}.prof")
            with open(path, "wb") as f:
                f.write(blob)
            trace.record_event("device-memory", path=path,
                               bytes=len(blob))
        except Exception:  # noqa: BLE001 — never fail a sweep over this
            pass

    failed, retried = [], []
    sweep_metrics: dict[str, dict] = {}
    try:
        for fname, job in jobs:
            if only is not None and fname[:-len(".csv")] not in only:
                continue
            name = fname[:-len(".csv")]
            path = os.path.join(args.out, fname)
            rows = None
            before = metrics.snapshot()
            t0 = time.perf_counter()
            for attempt in (1, 2):  # one retry: a flake can't zero the capture
                try:
                    faults.maybe_fail(f"sweep.{name}")
                    rows = job()
                    break
                except Exception as e:
                    rec = {"sweep": name, "attempt": attempt,
                           "error": type(e).__name__, "message": str(e)[:500]}
                    print(f"{fname}: FAILED attempt {attempt}/2 "
                          f"({type(e).__name__}: {e})", file=sys.stderr)
                    (retried if attempt == 1 else failed).append(rec)
                    trace.record_event("sweep-failed", sweep=name,
                                       attempt=attempt,
                                       error=type(e).__name__)
            if rows is None:
                continue
            ms = round((time.perf_counter() - t0) * 1e3, 1)
            trace.record_event("sweep-complete", sweep=name, rows=len(rows),
                               ms=ms)
            _memory_snapshot(name)
            sweep_metrics[name] = {"rows": len(rows), "ms": ms,
                                   "metrics": metrics.delta(
                                       before, metrics.snapshot())}
            sweeps.write_csv(rows, path)
            print(f"{path}: {len(rows)} rows")
    finally:
        if profiling:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
    manifest = {"failed": failed, "retried": retried}
    with open(os.path.join(args.out, "failures.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    with open(os.path.join(args.out, "metrics.json"), "w") as f:
        json.dump(sweep_metrics, f, indent=2, default=str)
    # nonzero only on a sweep failing BOTH attempts, so callers
    # (tpu_capture.sh) can record a sticky-vs-device failure instead of
    # seeing a green exit; retry-recovered flakes exit 0 and are still
    # auditable in failures.json
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
