"""State snapshotting and checkpoint/resume.

The reference's only persistence is debug/correctness snapshotting: text grid
dumps at init and final (``Grid::saveStateToFile``,
``hw/hw2/programming/2dHeat.cu:350-359``, per-rank in hw5 ``:549-557``), used
for BC debugging and offline N-vs-1 diffing (SURVEY §5).  This module keeps
that text-dump path (``grid/grid.py``) and adds a real binary
checkpoint/resume layer the reference lacked: iteration-stamped ``.npz``
snapshots that a long solve can be resumed from after interruption.
"""

from __future__ import annotations

import os

import numpy as np


def save_checkpoint(path: str, step: int, **arrays) -> None:
    """Atomic write of named arrays + step counter."""
    tmp = path + ".tmp"
    np.savez(tmp, __step=np.int64(step),
             **{k: np.asarray(v) for k, v in arrays.items()})
    # np.savez appends .npz to names without an extension
    if not tmp.endswith(".npz") and os.path.exists(tmp + ".npz"):
        tmp = tmp + ".npz"
    os.replace(tmp, path)


def load_checkpoint(path: str):
    """Returns (step, {name: array}) or None if absent."""
    if not os.path.exists(path):
        return None
    with np.load(path) as z:
        step = int(z["__step"])
        arrays = {k: z[k] for k in z.files if k != "__step"}
    return step, arrays


def run_with_checkpoints(step_fn, state, total_iters: int, path: str,
                         every: int = 0):
    """Drive ``state = step_fn(state, k_iters)`` in checkpointed chunks,
    resuming from ``path`` if a checkpoint exists.

    ``step_fn(state, k)`` must advance the state by k iterations.
    """
    start = 0
    loaded = load_checkpoint(path)
    if loaded is not None:
        start, arrays = loaded
        state = arrays["state"]
    every = every or total_iters
    it = start
    while it < total_iters:
        k = min(every, total_iters - it)
        state = step_fn(state, k)
        it += k
        save_checkpoint(path, it, state=np.asarray(state))
    return state
