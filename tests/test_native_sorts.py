import numpy as np
import pytest

native = pytest.importorskip("cme213_tpu.native")


@pytest.fixture(scope="module", autouse=True)
def built():
    try:
        from cme213_tpu.native.build import build_library

        build_library()
    except Exception as e:  # toolchain missing
        pytest.skip(f"native build unavailable: {e}")


@pytest.mark.parametrize("n", [0, 1, 100, 10_000, 1_000_003])
def test_merge_sort(n):
    rng = np.random.default_rng(n or 7)
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(np.int32)
    ref = np.sort(x)
    out = native.merge_sort(x.copy())
    np.testing.assert_array_equal(out, ref)


def test_merge_sort_thresholds():
    rng = np.random.default_rng(1)
    x = rng.integers(0, 1000, size=50_000).astype(np.int32)
    ref = np.sort(x)
    for st, mt in [(64, 64), (1024, 333), (100_000, 100_000)]:
        np.testing.assert_array_equal(
            native.merge_sort(x.copy(), st, mt), ref)


@pytest.mark.parametrize("n", [0, 1, 257, 100_000])
@pytest.mark.parametrize("num_bits", [4, 8, 11])
def test_radix_sort(n, num_bits):
    rng = np.random.default_rng(n + num_bits)
    x = rng.integers(0, 2**32, size=n, dtype=np.uint64).astype(np.uint32)
    ref = np.sort(x)
    np.testing.assert_array_equal(native.radix_sort(x.copy(), num_bits), ref)


def test_radix_sort_serial_matches_parallel():
    rng = np.random.default_rng(9)
    x = rng.integers(0, 2**32, size=65_537, dtype=np.uint64).astype(np.uint32)
    a = native.radix_sort(x.copy())
    b = native.radix_sort_serial(x.copy())
    np.testing.assert_array_equal(a, b)


def test_thread_control():
    native.set_threads(2)
    assert native.thread_count() == 2
    native.set_threads(4)
    assert native.thread_count() == 4
