#!/bin/bash
# Unattended device-capture loop for a round: wait for the tunnel, run
# compile bisect -> headline bench -> sweep capture, and — because the
# tunnel drops mid-sequence (round-3: first child preflight died after the
# watcher's own preflight passed) — RETRY the whole sequence until the
# headline bench lands a real number AND the sweep capture finishes,
# instead of giving up after one shot.
#
#   bash scripts/tpu_autocapture.sh [poll_interval_s] [deadline_s]
#
# Logs to /tmp/tpu_autocapture.log; touches /tmp/tpu_capture_done on
# success so an operator (or the session) can pick up tuning from there.
INTERVAL="${1:-60}"
DEADLINE="${2:-28800}"
cd "$(dirname "$0")/.."
. scripts/capture_lib.sh
start=$(date +%s)

# The deadline is a HARD chip-release guarantee, not just a stop-polling
# gate: the round driver runs its own bench on the real TPU at round end,
# and a capture attempt still holding the chip then would make the
# driver's preflight fail with the tunnel perfectly healthy.  Every
# stage's timeout is therefore capped by the time remaining.
remaining() {
  echo $(( (start + DEADLINE) - $(date +%s) ))
}
capped() {  # $1 = nominal stage timeout
  r=$(remaining)
  [ "$r" -lt 1 ] && r=1
  [ "$r" -lt "$1" ] && echo "$r" || echo "$1"
}
log=/tmp/tpu_autocapture.log
bisected=0
bisect_tries=0
polls=0
# stale markers from a prior run must not signal this round's progress
rm -f /tmp/tpu_evidence_done /tmp/tpu_capture_done
echo "$(date -Is) watcher started (interval ${INTERVAL}s," \
     "deadline ${DEADLINE}s)" >> "$log"

up() {
  timeout 90 python -c "
from cme213_tpu.core.platform import device_preflight
import jax, sys
sys.exit(0 if device_preflight(75) and jax.devices()[0].platform == 'tpu'
         else 1)" >/dev/null 2>&1
}

while true; do
  now=$(date +%s)
  if [ $((now - start)) -gt "$DEADLINE" ]; then
    echo "$(date -Is) GAVE UP" >> "$log"
    exit 1
  fi
  if ! up; then
    polls=$((polls + 1))
    # heartbeat: without it a never-opening tunnel leaves an empty log,
    # indistinguishable from a watcher that never ran
    if [ $((polls % 10)) = 0 ]; then
      echo "$(date -Is) still polling (attempt $polls, tunnel down)" \
        >> "$log"
    fi
    sleep "$INTERVAL"
    continue
  fi
  echo "$(date -Is) TPU UP — starting capture attempt" >> "$log"
  # tranche 1: the first ~120 s of any window bank (and git-commit) the
  # headline xla re-measure + one pipeline-k4 point + the transfer sweep
  # — so even a 3-minute window leaves committed device rows.  It doubles
  # as the gate: a device that can't hold these measurements can't hold
  # the full capture either.  SKIP_F32=1 below only skips the f32
  # headline when a COMPLETE bench_f32.json already exists from a prior
  # attempt; tranche rows are never copied into it.
  echo "== tranche 1 (first-window bank) ==" >> "$log"
  if timeout "$(capped 2700)" bash scripts/tpu_tranche1.sh bench_results \
      >> "$log" 2>&1; then
    # committed device evidence exists from here on
    touch /tmp/tpu_evidence_done
    mkdir -p bench_results
    echo "== full capture ==" >> "$log"
    if SKIP_F32=1 timeout "$(capped 14000)" \
        bash scripts/tpu_capture.sh bench_results >> "$log" 2>&1; then
      # full-capture evidence is on disk too (the marker was already set
      # after tranche 1; the session must still NOT start a tuning client
      # — the watcher owns the chip for the bisect below;
      # /tmp/tpu_capture_done means released)
      # the bisect deliberately offers the compiler over-budget cells, so
      # it runs LAST — a crash-wedged tunnel then costs nothing already
      # captured (headline + sweeps are on disk at this point)
      if [ "$bisected" = 0 ] && [ "$bisect_tries" -lt 3 ]; then
        bisect_tries=$((bisect_tries + 1))
        echo "== bisect (diagnostics, try $bisect_tries) ==" >> "$log"
        timeout "$(capped 3600)" python scripts/tpu_pipeline_bisect.py \
          > /tmp/tpu_bisect_last.txt 2>&1
        rc=$?
        cat /tmp/tpu_bisect_last.txt >> "$log"
        if [ "$rc" != 124 ] \
           && ! grep -qE ": (OK|FAIL)" /tmp/tpu_bisect_last.txt \
           && ! grep -qE "$DEVICE_ERR" /tmp/tpu_bisect_last.txt; then
          # ran to completion, no matrix rows, and no device signature in
          # the output: a sticky startup failure (a drop at startup DOES
          # leave a device signature and is retried) — retrying can't help
          echo "$(date -Is) bisect sticky-failed (no rows)" >> "$log"
          bisected=1
        elif [ "$rc" != 124 ] \
           && grep -qE ": (OK|FAIL)" /tmp/tpu_bisect_last.txt \
           && ! grep -qE "$DEVICE_ERR" /tmp/tpu_bisect_last.txt; then
          # actual matrix rows present AND no device signature anywhere:
          # conclusive.  The rows-exist conjunct catches the zero-row
          # startup drop; the blanket device-signature conjunct catches a
          # drop AFTER some OK rows (truncated matrix, rc!=124) — both
          # land in the retry path below, not here
          # (a timeout kill rc=124 means a truncated matrix — retried)
          bisected=1
        fi
      fi
      if [ "$bisected" = 0 ] && [ "$bisect_tries" -lt 3 ]; then
        # a drop (or timeout) truncated/poisoned the bisect matrix: the
        # capture itself is done (resumable — the re-invocation above is
        # a fast no-op), so loop back and re-run only the bisect
        echo "$(date -Is) bisect inconclusive — re-waiting" >> "$log"
        sleep "$INTERVAL"
        continue
      fi
      if [ "$bisected" = 0 ]; then
        # 3-try cap exhausted without a conclusive matrix — record that
        # so the last (possibly drop-poisoned) bisect output isn't read
        # as real compile failures
        echo "$(date -Is) bisect gave up after $bisect_tries tries —" \
             "matrix inconclusive" >> "$log"
        echo "INCONCLUSIVE: truncated/drop-poisoned after" \
             "$bisect_tries tries" >> /tmp/tpu_bisect_last.txt
      fi
      echo "$(date -Is) capture complete" >> "$log"
      touch /tmp/tpu_capture_done
      exit 0
    fi
    echo "$(date -Is) capture incomplete — re-waiting" >> "$log"
  else
    echo "$(date -Is) tranche 1 incomplete — re-waiting" >> "$log"
  fi
  sleep "$INTERVAL"
done
