"""Multi-process launcher — the ``mpirun -np N`` / PBS layer, as a tool.

The reference launches distributed runs with ``mpirun -np N ./2dHeat`` under
Torque/PBS (``hw/hw5/PA5_Handout.pdf`` §4, ``hw/hw4/programming/pa4.pbs``).
This is the JAX-native equivalent for single-machine and same-host testing:

    python -m cme213_tpu.dist.launch --np 2 [--devices-per-proc 2] -- \
        python my_workload.py

It picks a free coordinator port, spawns N copies of the command with the
standard launcher env (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
``JAX_PROCESS_ID``) that ``dist.multihost.initialize_multihost`` consumes,
prefixes each line of output with its rank (mpirun's ``-tag-output``), and
exits nonzero if any rank fails (fail-fast, the MPI_Abort analog: remaining
ranks are terminated when the first one dies).

On a real multi-host TPU pod each host runs its own process via the cluster
scheduler and ``--np``/``--proc-id`` come from it; this launcher covers the
reference's single-node ``nodes=1:ppn=N`` placement axis and CI, where
``--devices-per-proc`` fakes per-process chips with host CPU devices.
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import threading


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pump(rank: int, stream, out) -> None:
    for line in stream:
        out.write(f"[rank {rank}] {line}")
        out.flush()


def launch(np_procs: int, cmd: list[str], devices_per_proc: int | None = None,
           coordinator: str | None = None) -> int:
    """Spawn ``np_procs`` copies of ``cmd`` with launcher env; returns the
    first nonzero exit code (terminating the other ranks), else 0."""
    import time

    coordinator = coordinator or f"127.0.0.1:{free_port()}"
    procs: list[subprocess.Popen] = []
    pumps = []
    rc = 0
    try:
        for rank in range(np_procs):
            env = dict(os.environ,
                       JAX_COORDINATOR_ADDRESS=coordinator,
                       JAX_NUM_PROCESSES=str(np_procs),
                       JAX_PROCESS_ID=str(rank))
            if devices_per_proc:
                env["XLA_FLAGS"] = (
                    env.get("XLA_FLAGS", "")
                    + f" --xla_force_host_platform_device_count="
                      f"{devices_per_proc}").strip()
                env["JAX_PLATFORMS"] = "cpu"
            p = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                 stderr=subprocess.STDOUT, text=True)
            procs.append(p)
            t = threading.Thread(target=_pump,
                                 args=(rank, p.stdout, sys.stdout),
                                 daemon=True)
            t.start()
            pumps.append(t)

        # poll ALL ranks: a sequential wait() in rank order would miss a
        # higher rank dying first (e.g. rank 1 crashing while rank 0 blocks
        # in the coordinator handshake forever) and never fail fast
        live = set(range(np_procs))
        while live:
            for i in sorted(live):
                code = procs[i].poll()
                if code is None:
                    continue
                live.discard(i)
                if code and not rc:
                    rc = code
                    for q in procs:  # fail-fast: take survivors down
                        if q.poll() is None:
                            q.terminate()
            if live:
                time.sleep(0.05)
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()
        for t in pumps:
            t.join(timeout=5)
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="mpirun-style launcher for multi-process JAX runs")
    ap.add_argument("--np", dest="np_procs", type=int, required=True,
                    help="number of processes (MPI world size)")
    ap.add_argument("--devices-per-proc", type=int, default=None,
                    help="fake this many CPU devices per process "
                         "(testing without a pod)")
    ap.add_argument("--coordinator", default=None,
                    help="host:port (default: 127.0.0.1:<free port>)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to launch (prefix with --)")
    args = ap.parse_args(argv)
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        ap.error("no command given (append: -- python your_script.py)")
    return launch(args.np_procs, cmd, args.devices_per_proc,
                  args.coordinator)


if __name__ == "__main__":
    raise SystemExit(main())
