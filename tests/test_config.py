import math

import pytest

from cme213_tpu.config import GridMethod, SimParams


def ref_dt(order, alpha, dx, dy):
    # reproduce calcDtCFL (2dHeat.cu:206-228) independently
    m = 0.5 - 0.0001
    if order == 2:
        return m * (dx * dx * dy * dy) / (alpha * (dx * dx + dy * dy))
    if order == 4:
        return m * (12 * dx * dx * dy * dy) / (16 * alpha * (dx * dx + dy * dy))
    if order == 8:
        return m * (5040 * dx * dx * dy * dy) / (8064 * alpha * (dx * dx + dy * dy))


@pytest.mark.parametrize("order,border", [(2, 1), (4, 2), (8, 4)])
def test_cfl_and_geometry(order, border):
    p = SimParams(nx=100, ny=50, lx=2.0, ly=1.0, alpha=0.3, order=order)
    dx = 2.0 / 99
    dy = 1.0 / 49
    assert p.dx == pytest.approx(dx)
    assert p.dy == pytest.approx(dy)
    assert p.dt == pytest.approx(ref_dt(order, 0.3, dx, dy))
    assert p.border_size == border
    assert p.gx == 100 + 2 * border
    assert p.gy == 50 + 2 * border
    # CFL numbers under the stability threshold
    if order == 2:
        assert p.xcfl + p.ycfl < 0.5
    assert p.xcfl > 0 and p.ycfl > 0


def test_defaults_match_reference():
    # simParams::simParams() defaults (2dHeat.cu:133-162)
    p = SimParams()
    assert (p.nx, p.ny) == (10, 10)
    assert p.bc == (0.0, 10.0, 0.0, 10.0)
    assert p.ic == 5.0
    assert p.order == 2 and p.border_size == 1


def test_unsupported_order():
    with pytest.raises(ValueError):
        SimParams(order=3)


def test_file_roundtrip(tmp_path):
    p = SimParams(nx=64, ny=32, lx=3.0, ly=2.0, alpha=0.7, iters=13, order=4,
                  ic=2.5, bc_top=1.0, bc_left=2.0, bc_bottom=3.0, bc_right=4.0)
    f = tmp_path / "params.in"
    p.to_file(str(f))
    q = SimParams.from_file(str(f))
    assert q == p


def test_file_roundtrip_distributed(tmp_path):
    p = SimParams(nx=64, ny=32, order=8, grid_method=GridMethod.BLOCKS_2D,
                  synchronous=False)
    f = tmp_path / "params.in"
    p.to_file(str(f), distributed=True)
    q = SimParams.from_file(str(f), distributed=True)
    assert q == p
    assert q.grid_method == GridMethod.BLOCKS_2D and not q.synchronous
