"""Numeric-health observatory (``core/numerics.py``): shadow conformance
sampling, drift error budgets, output sentinels, convergence tracing,
and the ``numerics`` CLI gate.

The anchor test is the full loop the subsystem exists for: an injected
``drift:`` fault perturbs a serving rung's outputs *below* the ``wrong:``
blow-up threshold, the shadow sampler catches it against the reference
rung, the per-(op, rung) error budget burns, the ladder gate demotes the
rung — and served results are bitwise-identical to the reference again.
All CPU-deterministic: count-window budgets, seeded sampling, fault
clauses instead of real numeric decay.
"""

import json

import numpy as np
import pytest

from cme213_tpu.core import faults, metrics, trace
from cme213_tpu.core import numerics
from cme213_tpu.core.resilience import FailureKind, VirtualClock
from cme213_tpu.serve import Server
from cme213_tpu.serve import slo as slo_mod


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.clear_events()
    metrics.reset()
    numerics.reset()
    yield
    faults.reset()
    numerics.reset()
    metrics.reset()


class FloatEchoAdapter:
    """Two-rung echo over float payloads: ``fast`` and ``safe`` both
    return the payload array unchanged, so the reference rung (``safe``)
    is bitwise-correct by construction and any drift on ``fast`` comes
    from an injected ``drift:serve.echo.fast`` clause."""

    op = "echo"

    def __init__(self):
        self.calls: list[tuple[str, int]] = []

    def shape_class(self, payload, coarse: bool = False) -> str:
        return "any" if coarse else payload[0]

    def rungs(self, degraded: bool = False):
        return ("safe",) if degraded else ("fast", "safe")

    def run_batch(self, payloads, rung: str, coarse: bool = False):
        self.calls.append((rung, len(payloads)))
        return [np.array(p[1], dtype=np.float32) for p in payloads]

    def preflight_builder(self, payloads, rung, coarse=False):
        return None


def echo_server(**kw):
    adapter = FloatEchoAdapter()
    kw.setdefault("clock", VirtualClock())
    return Server(adapters={"echo": adapter}, **kw), adapter


# ------------------------------------------------- the full shadow loop

def test_drift_fault_caught_budget_burns_rung_demoted(monkeypatch):
    monkeypatch.setenv(numerics.SHADOW_RATE_ENV, "1")
    server, adapter = echo_server(max_batch=4)
    payloads = [np.full(8, float(i + 1), dtype=np.float32)
                for i in range(12)]
    results = []
    with faults.injected("drift:serve.echo.fast"):
        for i, payload in enumerate(payloads):
            server.submit("echo", ("k", payload))
            results.extend(server.step())

    assert [r.status for r in results] == ["ok"] * 12

    # phase 1: the drifting rung serves, every shadow sample is over
    drift_events = trace.events("numeric-drift")
    assert len(drift_events) >= numerics.budget().min_samples
    assert all(e["op"] == "serve.echo" and e["rung"] == "fast"
               and e["over_budget"] for e in drift_events)
    # the perturbation is small (1 + 1e-3): below wrong:'s blow-up, but
    # far above the shadow tolerance
    assert all(0 < e["rel_l2"] < 1e-2 for e in drift_events)

    # phase 2: the budget burns and the rung is demoted, sticky
    burns = trace.events("drift-budget-burn")
    assert len(burns) == 1
    assert burns[0]["op"] == "serve.echo" and burns[0]["rung"] == "fast"
    assert numerics.demoted("serve.echo", "fast")
    snap = numerics.last_drift()
    assert snap["demoted"] == ["serve.echo|fast"]
    assert snap["budget"]["serve.echo|fast"]["burning"]

    # phase 3: post-demotion requests serve on the reference rung and
    # match the submitted payload bitwise (the drift clause still
    # targets fast — it simply no longer runs)
    demoted_at = next(i for i, r in enumerate(results) if r.rung == "safe")
    assert demoted_at <= numerics.budget().min_samples
    for i, r in enumerate(results[demoted_at:], start=demoted_at):
        assert r.rung == "safe"
        np.testing.assert_array_equal(np.asarray(r.value), payloads[i])
    # pre-demotion results really were drifted — the fault was live
    assert not np.array_equal(np.asarray(results[0].value), payloads[0])

    # the reference rung is never shadow-sampled against itself
    assert all(e["rung"] == "fast" for e in trace.events("numeric-drift"))


def test_clean_serving_has_zero_drift_over_budget(monkeypatch):
    monkeypatch.setenv(numerics.SHADOW_RATE_ENV, "1")
    server, adapter = echo_server(max_batch=4)
    for i in range(6):
        server.submit("echo", ("k", np.full(4, float(i + 1), np.float32)))
        server.step()
    drift_events = trace.events("numeric-drift")
    assert len(drift_events) == 6
    assert not any(e["over_budget"] for e in drift_events)
    assert not trace.events("drift-budget-burn")
    assert numerics.last_drift()["demoted"] == []


def test_shadow_off_by_default():
    server, adapter = echo_server(max_batch=4)
    server.submit("echo", ("k", np.ones(4, np.float32)))
    server.step()
    assert not trace.events("numeric-drift")
    # only the serving rung ran — no reference re-execution happened
    assert [c[0] for c in adapter.calls] == ["fast"]


# -------------------------------------------------- seeded sampling

def test_should_sample_deterministic_across_processes():
    rids = [str(i) for i in range(400)]
    rank0 = {r for r in rids if numerics.should_sample(r, rate=4, trace="T")}
    rank1 = {r for r in rids if numerics.should_sample(r, rate=4, trace="T")}
    assert rank0 == rank1                  # gangs sample the same requests
    assert 0 < len(rank0) < len(rids)      # it is a sample, not all/none
    other = {r for r in rids if numerics.should_sample(r, rate=4, trace="U")}
    assert other != rank0                  # keyed by trace context
    assert all(numerics.should_sample(r, rate=1, trace="T") for r in rids)
    assert not any(numerics.should_sample(r, rate=0, trace="T") for r in rids)


def test_shadow_rate_env_parsing(monkeypatch):
    monkeypatch.delenv(numerics.SHADOW_RATE_ENV, raising=False)
    assert numerics.shadow_rate() == 0
    monkeypatch.setenv(numerics.SHADOW_RATE_ENV, "8")
    assert numerics.shadow_rate() == 8
    monkeypatch.setenv(numerics.SHADOW_RATE_ENV, "junk")
    assert numerics.shadow_rate() == 0
    monkeypatch.setenv(numerics.SHADOW_RATE_ENV, "-3")
    assert numerics.shadow_rate() == 0


# ------------------------------------------------------ drift measure

def test_measure_drift():
    a = np.ones(8, dtype=np.float32)
    assert numerics.measure_drift(a, a) == (0.0, 0)
    rel, ulps = numerics.measure_drift(a * np.float32(1.001), a)
    assert 0 < rel < 2e-3 and ulps > 0
    rel, ulps = numerics.measure_drift(np.ones(4, np.float32),
                                       np.ones(5, np.float32))
    assert rel == float("inf") and ulps == -1
    rel, ulps = numerics.measure_drift(np.array([np.nan], np.float32),
                                       np.array([1.0], np.float32))
    assert rel == float("inf") and ulps == -1
    # integer outputs: rel-L2 over the cast, no ulp notion
    assert numerics.measure_drift(np.arange(4), np.arange(4)) == (0.0, 0)


# ------------------------------------------------------- error budget

def test_budget_burns_after_sustained_over_and_recovers():
    b = numerics.DriftBudget(target=0.1, short_n=4, long_n=8,
                             min_samples=4, burn_threshold=2.0,
                             hysteresis=0.5)
    burning = False
    for _ in range(4):
        burning = b.observe("op", "r", True, rel_l2=0.5)
    assert burning and b.burning("op", "r")
    assert len(trace.events("drift-budget-burn")) == 1
    # clean samples flush the short window under threshold * hysteresis
    for _ in range(4):
        burning = b.observe("op", "r", False)
    assert not burning and not b.burning("op", "r")
    assert len(trace.events("drift-budget-ok")) == 1
    st = b.state()["op|r"]
    assert st["samples"] == 8 and st["over"] == 4


def test_budget_needs_min_samples():
    b = numerics.DriftBudget(target=0.1, short_n=4, long_n=8, min_samples=6)
    for _ in range(5):
        assert not b.observe("op", "r", True)
    assert b.observe("op", "r", True)   # the 6th over-sample fires


def test_budget_rejects_nonpositive_target():
    with pytest.raises(ValueError):
        numerics.DriftBudget(target=0.0)


# ---------------------------------------------------------- sentinels

class _SpyBreaker:
    def __init__(self):
        self.calls = []

    def record_failure(self, op, rung, kind):
        self.calls.append((op, rung, kind))


def test_sentinel_nan_trips_breaker():
    br = _SpyBreaker()
    bad = numerics.sentinel("serve.echo", "fast",
                            [np.array([1.0, np.nan, np.inf], np.float32)],
                            breaker=br)
    assert bad == 2
    ev = trace.events("numeric-sentinel")[-1]
    assert ev["kind"] == "non-finite" and ev["count"] == 2 and ev["size"] == 3
    assert br.calls == [("serve.echo", "fast", FailureKind.NUMERIC)]
    assert metrics.counter("numerics.sentinel.tripped").value == 1


def test_sentinel_range_check():
    bad = numerics.sentinel("op", "r", [np.array([0.5, 2.0], np.float32)],
                            lo=0.0, hi=1.0)
    assert bad == 1
    assert trace.events("numeric-sentinel")[-1]["kind"] == "out-of-range"


def test_sentinel_clean_batch_is_silent():
    assert numerics.sentinel("op", "r", [np.ones(16, np.float32)],
                             lo=0.0, hi=2.0) == 0
    assert not trace.events("numeric-sentinel")
    # non-float outputs are skipped entirely (bitwise workloads)
    assert numerics.sentinel("op", "r", [np.arange(8, dtype=np.uint8)]) == 0


# --------------------------------------------------------- convergence

def test_convergence_tracker_stall_verdict():
    tr = numerics.ConvergenceTracker("solve", stall_epochs=3)
    for step, res in enumerate((1.0, 0.5, 0.25)):
        tr.step(step, res, res, 10.0)
    assert not tr.stalled
    for step in range(3, 6):               # residual stops improving
        tr.step(step, 0.25, 0.0, 10.0)
    assert tr.stalled
    evs = trace.events("solver-progress")
    assert len(evs) == 6
    assert evs[0]["op"] == "solve" and evs[-1]["step"] == 5
    # improvement resets the stall counter
    tr.step(6, 0.1, 0.15, 10.0)
    assert not tr.stalled


def test_progress_from_states_residual_math():
    tr = numerics.ConvergenceTracker("solve")
    old = np.ones((4, 4), np.float32)
    new = old * np.float32(1.5)
    numerics.progress_from_states(tr, 3, old, new, iters=4, elapsed_s=2.0)
    ev = trace.events("solver-progress")[-1]
    assert ev["step"] == 3 and ev["iters_per_s"] == 2.0
    assert ev["residual"] == pytest.approx(0.5 / 1.5, rel=1e-6)
    # mismatched shapes (resharded state) are skipped, never raised
    numerics.progress_from_states(tr, 4, np.ones(3), np.ones(5), 1, 1.0)
    assert len(trace.events("solver-progress")) == 1


def test_checkpointed_solves_emit_progress(tmp_path):
    from cme213_tpu.apps.heat2d import run_heat_checkpointed
    from cme213_tpu.config import SimParams

    run_heat_checkpointed(SimParams(nx=16, ny=16, order=2, iters=6),
                          str(tmp_path / "ckpt"), every=2)
    evs = [e for e in trace.events("solver-progress")
           if e["op"] == "heat2d"]
    assert len(evs) == 3                    # one per chunk
    assert all(e["residual"] >= 0 for e in evs)


# ------------------------------------------------- fleet-level SLO kind

def test_slo_drift_rate_objective_burns():
    clock = VirtualClock()
    mon = slo_mod.from_flags(clock, drift_rate=0.1, short_s=5.0,
                             long_s=10.0, min_samples=4)
    for _ in range(4):
        mon.observe(latency_ms=1.0, drift=True)
        clock.advance(0.1)
    state = mon.evaluate()
    assert state["drift-rate"]["burning"]
    assert any(e["objective"] == "drift-rate"
               for e in trace.events("slo-burn"))
    # non-shadow samples are invisible to the drift objective
    mon2 = slo_mod.from_flags(clock, drift_rate=0.1, min_samples=1)
    mon2.observe(latency_ms=1.0)
    assert mon2.evaluate()["drift-rate"]["burn_short"] is None


# ------------------------------------------------------ CLI + summary

def _write_sink(tmp_path):
    recs = [
        {"event": "numeric-drift", "t": 1.0, "op": "serve.echo",
         "rung": "fast", "shape_class": "k", "rel_l2": 0.5, "max_ulps": 9,
         "over_budget": True},
        {"event": "numeric-drift", "t": 2.0, "op": "serve.echo",
         "rung": "fast", "shape_class": "k", "rel_l2": 0.0, "max_ulps": 0,
         "over_budget": False},
        {"event": "drift-budget-burn", "t": 3.0, "op": "serve.echo",
         "rung": "fast", "burn_short": 10.0, "burn_long": 10.0,
         "threshold": 2.0},
        {"event": "numeric-sentinel", "t": 4.0, "op": "serve.heat",
         "rung": "xla", "kind": "non-finite", "count": 3, "size": 64},
        {"event": "solver-progress", "t": 5.0, "op": "heat2d", "step": 1,
         "residual": 0.5, "delta_norm": 1.0, "iters_per_s": 3.0},
        {"event": "solver-progress", "t": 6.0, "op": "heat2d", "step": 2,
         "residual": 0.25, "delta_norm": 0.5, "iters_per_s": 3.0},
    ]
    sink = tmp_path / "trace.jsonl"
    sink.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    return str(sink)


def test_numerics_cli_report_and_gates(tmp_path, capsys):
    from cme213_tpu import numerics_cli

    sink = _write_sink(tmp_path)
    assert numerics_cli.main(["report", sink, "--max-over-budget", "1"]) == 0
    out = capsys.readouterr().out
    assert "2 shadow sample(s)" in out and "DEMOTED serve.echo.fast" in out
    assert "solver heat2d" in out and "converging" in out

    assert numerics_cli.main(["report", sink, "--max-over-budget", "0"]) == 1
    assert "over the drift budget" in capsys.readouterr().err
    assert numerics_cli.main(["report", sink, "--min-samples", "3"]) == 1
    assert numerics_cli.main(["report", sink, "--forbid-stall"]) == 0

    doc = numerics_cli.report([sink])
    assert doc["numerics"]["samples"] == 2
    assert doc["numerics"]["over_budget"] == 1
    assert doc["numerics"]["demotions"] == ["serve.echo.fast"]
    assert doc["numerics"]["sentinels"]["trips"] == 1
    assert doc["convergence"]["heat2d"]["epochs"] == 2
    assert not doc["convergence"]["heat2d"]["stalled"]


def test_numerics_cli_forbid_stall_gate(tmp_path):
    from cme213_tpu import numerics_cli

    recs = [{"event": "solver-progress", "t": float(i), "op": "s",
             "step": i, "residual": 1.0, "delta_norm": 0.0,
             "iters_per_s": 1.0} for i in range(7)]
    sink = tmp_path / "stalled.jsonl"
    sink.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    assert numerics_cli.main(["report", str(sink)]) == 0
    assert numerics_cli.main(["report", str(sink), "--forbid-stall"]) == 1
    doc = numerics_cli.report([str(sink)])
    assert doc["convergence"]["s"]["stalled"]


def test_trace_summary_numeric_sections(tmp_path):
    import io

    from cme213_tpu.trace_cli import load_events, summarize

    sink = _write_sink(tmp_path)
    buf = io.StringIO()
    agg = summarize(load_events([sink]), out=buf)
    text = buf.getvalue()
    assert "numeric health:" in text and "convergence:" in text
    assert agg["numerics"]["samples"] == 2
    assert agg["numerics"]["drift"]["serve.echo.fast"]["over_budget"] == 1
    assert agg["convergence"]["heat2d"]["last_residual"] == 0.25
    # --require consumes event names through the counts table
    assert agg["counts"]["numeric-drift"] == 2


def test_flight_dump_embeds_drift_snapshot(tmp_path, monkeypatch):
    from cme213_tpu.core import flight

    monkeypatch.setenv(flight.FLIGHT_DIR_ENV, str(tmp_path))
    b = numerics.budget()
    for _ in range(b.min_samples):
        b.observe("serve.echo", "fast", True, rel_l2=0.5)
    numerics._DEMOTED.add(("serve.echo", "fast"))
    path = flight.dump("test-reason")
    doc = json.loads(open(path).read())
    assert doc["numerics"]["demoted"] == ["serve.echo|fast"]
    assert doc["numerics"]["budget"]["serve.echo|fast"]["burning"]
