"""Profiling/tracing hooks.

The reference's tracing is labeled phase timers around every stage plus
offline derived metrics (SURVEY §5).  ``PhaseTimer`` covers that; this module
adds the device-level profile the CUDA events couldn't give: a context
manager around ``jax.profiler`` producing an XPlane trace (viewable in
TensorBoard/Perfetto) for kernel-level overlap verification — which SURVEY §7
calls out as the way "async" overlap must be verified on TPU.

It also carries the structured event log of the resilience layer: op
failures (``core/errors.check_op``), fallback-ladder demotions and retries
(``core/resilience.py``), checkpoint quarantines (``core/checkpoint.py``)
and injected faults (``core/faults.py``) all flow through ``record_event``
as dicts, so capture logs can be grepped for machine-readable records
instead of formatted strings.  Set ``CME213_TRACE_FILE`` to also append
each event as a JSON line (the capture-log path).
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager

_EVENTS: list[dict] = []
_LOCK = threading.Lock()


def record_event(event: str, **fields) -> dict:
    """Append a structured event to the in-process log (and the
    ``CME213_TRACE_FILE`` JSON-lines sink, when set).  Returns the record."""
    rec = {"event": event, "t": round(time.time(), 6), **fields}
    with _LOCK:
        _EVENTS.append(rec)
    path = os.environ.get("CME213_TRACE_FILE")
    if path:
        try:
            with open(path, "a") as f:
                f.write(json.dumps(rec, default=str) + "\n")
        except OSError:
            pass  # a broken sink must never take down the workload
    return rec


def events(event: str | None = None) -> list[dict]:
    """Snapshot of recorded events, optionally filtered by event name."""
    with _LOCK:
        snap = list(_EVENTS)
    if event is None:
        return snap
    return [e for e in snap if e["event"] == event]


def clear_events() -> None:
    with _LOCK:
        _EVENTS.clear()


@contextmanager
def device_trace(log_dir: str):
    """Capture a device profile of the enclosed block into ``log_dir``."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
