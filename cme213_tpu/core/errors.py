"""Op-level error barriers.

TPU-native analog of the reference's ``check_launch(name)`` (sync +
``cudaGetLastError`` + abort, ``hw/hw1/programming/mp1-util.h:8-18``) and
``MPI_SAFE_CALL`` (``hw/hw5/programming/2dHeat.cpp:45-51``).  JAX device
errors surface lazily on materialization; ``check_op`` forces them at a named
point so failures carry the op label, like the reference's kernel names.

Unlike the reference's abort-on-first-error, a failed barrier here emits a
structured record — op name, exception class, elapsed ms — through the
``core/trace.py`` event log (and any ``PhaseTimer`` passed in) before
raising, so the resilience layer's demotions and retries are observable in
capture logs instead of vanishing into a formatted string.
"""

from __future__ import annotations

import time

import jax

from .trace import record_event


class FrameworkError(RuntimeError):
    """Named-op failure; ``.record`` holds the structured trace record."""

    record: dict | None = None


class DataValidationError(FrameworkError):
    """External input data failed an invariant check at ingestion (corrupt
    or truncated matrix file, inconsistent header, out-of-range indices,
    non-finite values).  Raised *at the boundary* instead of letting the
    garbage flow downstream into kernels; ``.record`` carries the
    structured ``data-validation`` trace record (source, invariant,
    detail)."""


def data_error(source: str, invariant: str, detail: str) -> DataValidationError:
    """Build a DataValidationError with its structured trace record
    emitted (``data-validation`` event: where, which invariant, what)."""
    rec = record_event("data-validation", source=source,
                       invariant=invariant, detail=detail[:300])
    err = DataValidationError(f"{source}: {invariant}: {detail}")
    err.record = rec
    return err


def check_op(name: str, *arrays, timer=None):
    """Block until ``arrays`` are ready; re-raise any device error with ``name``.

    Returns the arrays (single array unwrapped) so it can be used inline::

        out = check_op("gpu shift cypher", shift(x))

    With ``timer`` (a ``PhaseTimer``), the blocking time is appended to the
    timer's records under ``name`` — success or failure — so barrier costs
    show up next to the phases they guard.  On failure the structured
    record ``{event: "op-failure", op, error, ms}`` is emitted through
    ``core/trace.record_event`` and attached to the raised
    ``FrameworkError`` as ``.record``.
    """
    start = time.perf_counter()
    try:
        for a in arrays:
            jax.block_until_ready(a)
    except Exception as e:  # XlaRuntimeError et al.
        ms = (time.perf_counter() - start) * 1e3
        rec = record_event("op-failure", op=name, error=type(e).__name__,
                           ms=round(ms, 3), message=str(e)[:300])
        if timer is not None:
            from .timing import PhaseRecord

            timer.records.append(PhaseRecord(name, ms))
        err = FrameworkError(f"error in {name}: {e}")
        err.record = rec
        raise err from e
    if timer is not None:
        from .timing import PhaseRecord

        timer.records.append(
            PhaseRecord(name, (time.perf_counter() - start) * 1e3))
    if len(arrays) == 1:
        return arrays[0]
    return arrays
