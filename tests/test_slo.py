"""SLO burn-rate monitoring (``serve/slo.py``): objective validation,
the two-window AND filter (a short burst alone never fires), recovery
hysteresis (no flapping at the threshold), and the server integration —
sustained overload trips degraded mode through the SLO hook and recovery
releases it.  Every test runs on a ``VirtualClock``; no wall-time."""

import io

import pytest

from cme213_tpu.core import faults, metrics, trace
from cme213_tpu.core.resilience import VirtualClock
from cme213_tpu.serve import Objective, Server, SLOMonitor
from cme213_tpu.serve.slo import from_flags


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.clear_events()
    metrics.reset()
    yield
    faults.reset()
    metrics.reset()


def monitor(objective, **kw):
    clock = VirtualClock()
    kw.setdefault("short_window_s", 5.0)
    kw.setdefault("long_window_s", 60.0)
    kw.setdefault("min_samples", 5)
    return SLOMonitor([objective], clock=clock, **kw), clock


# ------------------------------------------------------------ objectives

def test_objective_validates_kind_and_target():
    with pytest.raises(ValueError, match="unknown objective kind"):
        Objective("x", "p42_latency", 1.0)
    with pytest.raises(ValueError, match="target must be > 0"):
        Objective("x", "shed_rate", 0.0)


def test_from_flags_builds_requested_objectives_only():
    assert from_flags() is None
    mon = from_flags(p99_ms=50.0, shed_rate=0.1)
    assert [o.name for o in mon.objectives] == ["p99-latency", "shed-rate"]
    assert [o.kind for o in mon.objectives] == ["p99_latency_ms", "shed_rate"]


# ------------------------------------------------------------- transitions

def test_burn_fires_on_sustained_violation():
    mon, clock = monitor(Objective("p99", "p99_latency_ms", 100.0))
    for _ in range(10):
        clock.advance(0.1)
        mon.observe(latency_ms=500.0)
    state = mon.evaluate()
    assert mon.burning and state["p99"]["burning"]
    (ev,) = trace.events("slo-burn")
    assert ev["objective"] == "p99"
    assert ev["burn_short"] >= ev["threshold"]
    # the worst short-window burn is exported as a gauge
    assert metrics.gauge("serve.slo.burn").value == ev["burn_short"]


def test_min_samples_gate_blocks_early_fire():
    mon, _ = monitor(Objective("p99", "p99_latency_ms", 100.0),
                     min_samples=10)
    for _ in range(9):
        mon.observe(latency_ms=500.0)
    mon.evaluate()
    assert not mon.burning and not trace.events("slo-burn")
    mon.observe(latency_ms=500.0)            # the tenth sample arms it
    mon.evaluate()
    assert mon.burning


def test_short_burst_alone_does_not_fire():
    """The two-window AND: the long window must agree the problem is
    sustained before the monitor fires."""
    mon, clock = monitor(
        Objective("p99", "p99_latency_ms", 100.0, budget=0.2))
    for _ in range(40):                       # 40s of healthy history
        clock.advance(1.0)
        mon.observe(latency_ms=10.0)
        mon.observe(latency_ms=10.0)
    for _ in range(10):                       # burst: short window only
        mon.observe(latency_ms=500.0)
    mon.evaluate()
    assert not mon.burning and not trace.events("slo-burn")
    # sustained violation degrades the long window too -> fires ONCE
    for _ in range(15):
        clock.advance(1.0)
        for _ in range(6):
            mon.observe(latency_ms=500.0)
        mon.evaluate()
    assert mon.burning
    assert len(trace.events("slo-burn")) == 1


def test_recovery_hysteresis_no_flap():
    """Recovery needs the short burn to fall to threshold*hysteresis —
    a burn hovering between the recovery bound and the fire threshold
    produces neither a new burn nor a premature slo-ok."""
    mon, clock = monitor(Objective("shed", "shed_rate", 0.1))
    for _ in range(10):
        mon.observe(shed=True)                # rate 1.0 -> burn 10
    mon.evaluate()
    assert mon.burning and len(trace.events("slo-burn")) == 1
    clock.advance(6.0)                        # old samples leave the
    for i in range(20):                       # short window
        mon.observe(shed=(i < 3))             # rate 0.15 -> burn 1.5
    mon.evaluate()
    assert mon.burning                        # 1.0 < 1.5 < 2.0: hold
    assert len(trace.events("slo-burn")) == 1
    assert not trace.events("slo-ok")
    clock.advance(6.0)
    for i in range(20):
        mon.observe(shed=(i < 1))             # rate 0.05 -> burn 0.5
    mon.evaluate()
    assert not mon.burning
    assert len(trace.events("slo-ok")) == 1
    mon.evaluate()                            # stable: no flap
    assert len(trace.events("slo-ok")) == 1
    assert len(trace.events("slo-burn")) == 1


def test_error_rate_objective_and_state():
    mon, _ = monitor(Objective("err", "error_rate", 0.05))
    for _ in range(10):
        mon.observe(latency_ms=10.0)
        mon.observe(failed=True)              # rate 0.5 -> burn 10
    out = mon.evaluate()
    assert mon.burning and out["err"]["kind"] == "error_rate"
    assert mon.state() == out


def test_empty_and_shed_only_windows_burn_nothing():
    mon, _ = monitor(Objective("p99", "p99_latency_ms", 100.0))
    out = mon.evaluate()
    assert out["p99"]["burn_short"] is None and not mon.burning
    assert metrics.gauge("serve.slo.burn").value == 0.0
    for _ in range(10):                       # shed samples carry no
        mon.observe(shed=True)                # latency: excluded from p99
    out = mon.evaluate()
    assert out["p99"]["burn_short"] is None and not mon.burning


# ------------------------------------------------------ server integration

class _EchoAdapter:
    op = "echo"

    def shape_class(self, payload, coarse=False):
        return "any" if coarse else payload[0]

    def rungs(self, degraded=False):
        return ("fast",) if degraded else ("fast", "safe")

    def run_batch(self, payloads, rung, coarse=False):
        return [p[1] for p in payloads]

    def preflight_builder(self, payloads, rung, coarse=False):
        return None


def test_server_slo_burn_trips_and_releases_degraded_mode():
    """The acceptance cycle: sustained injected overload (every batch
    200ms against a 50ms objective) trips slo-burn -> degraded mode via
    the SLO hook; once the violations age out of the windows, slo-ok
    fires and degraded mode exits."""
    clock = VirtualClock()
    mon = SLOMonitor([Objective("p99", "p99_latency_ms", 50.0)],
                     clock=clock, short_window_s=30.0, long_window_s=30.0,
                     burn_threshold=2.0, min_samples=4)
    server = Server(adapters={"echo": _EchoAdapter()}, clock=clock,
                    max_batch=1, slo=mon)
    with faults.injected("slow:serve.echo:200:1:8"):
        for v in range(6):
            server.submit("echo", ("k", v))
            server.step()
    assert server.degraded and server._degrade_reason == "slo-burn"
    (ev,) = trace.events("slo-burn")
    assert ev["objective"] == "p99"
    begun = [e for e in trace.events("span-begin")
             if e.get("span") == "degraded-mode"]
    assert begun and begun[-1]["reason"] == "slo-burn"
    # recovery: the bad samples age out, fast traffic resumes
    clock.advance(31.0)
    for v in range(3):
        server.submit("echo", ("k", v))
        server.step()
    assert trace.events("slo-ok") and not mon.burning
    assert not server.degraded and server._degrade_reason is None
    assert len(trace.events("slo-burn")) == 1   # no flap across the cycle


def test_trace_summary_reports_slo_section():
    from cme213_tpu.trace_cli import summarize

    mon, clock = monitor(Objective("shed", "shed_rate", 0.1))
    for _ in range(10):
        mon.observe(shed=True)
    mon.evaluate()
    clock.advance(6.0)
    for _ in range(20):
        mon.observe(shed=False)
    mon.evaluate()
    out = io.StringIO()
    summary = summarize(trace.events(), out=out)
    assert summary["slo"]["burns"] == 1 and summary["slo"]["oks"] == 1
    assert summary["slo"]["objectives"] == ["shed"]
    assert summary["slo"]["last_burn"]["objective"] == "shed"
    text = out.getvalue()
    assert "slo: 1 burn(s), 1 recover(ies) [shed]" in text
