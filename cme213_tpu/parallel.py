"""Parallelism/distribution layer (alias module).

Canonical home: ``cme213_tpu.dist`` (meshes, halo exchange, distributed heat
steps, multi-device segmented scan, multi-host init).
"""

from .dist import *  # noqa: F401,F403
from .dist import multihost  # noqa: F401
from .dist import __all__  # noqa: F401
