import numpy as np
import pytest

from cme213_tpu.core import PhaseTimer, almost_equal_ulps, bandwidth_gbs, ulp_distance
from cme213_tpu.core.timing import time_fn


def test_ulp_distance_adjacent_floats():
    a = np.float32(1.0)
    b = np.nextafter(a, np.float32(2.0))
    assert ulp_distance(a, b) == 1
    assert ulp_distance(a, a) == 0


def test_ulp_distance_across_zero():
    # -0.0 and +0.0 are 1 apart in the two's-complement ordering the
    # reference uses (mp1-util.h:44-61): keys are adjacent.
    a = np.float32(-0.0)
    b = np.float32(0.0)
    assert ulp_distance(a, b) <= 1
    tiny_neg = np.nextafter(np.float32(0.0), np.float32(-1.0))
    tiny_pos = np.nextafter(np.float32(0.0), np.float32(1.0))
    assert ulp_distance(tiny_neg, tiny_pos) <= 3


def test_almost_equal_ulps_vector():
    a = np.linspace(-5, 5, 101, dtype=np.float32)
    b = a.copy()
    for _ in range(5):
        b = np.nextafter(b, np.float32(np.inf))
    assert almost_equal_ulps(a, b, max_ulps=10).all()
    assert not almost_equal_ulps(a, b, max_ulps=3).any()


def test_ulp_distance_float64():
    a = np.float64(3.141592653589793)
    b = np.nextafter(a, 10.0)
    assert ulp_distance(a, b) == 1


def test_nan_never_equal():
    assert not almost_equal_ulps(np.float32(np.nan), np.float32(np.nan)).any()


def test_dtype_mismatch_raises():
    with pytest.raises(ValueError):
        ulp_distance(np.float32(1.0), np.float64(1.0))


def test_phase_timer():
    import jax.numpy as jnp

    t = PhaseTimer()
    with t.phase("add") as ph:
        out = jnp.ones(16) + 1
        ph.block(out)
    assert t.ms("add") >= 0
    assert t.last_ms("add") == t.records[-1].ms


def test_time_fn_and_bandwidth():
    import jax.numpy as jnp

    ms = time_fn(lambda x: x + 1, jnp.ones(1024))
    assert ms > 0
    assert bandwidth_gbs(1e9, 1000.0) == pytest.approx(1.0)
