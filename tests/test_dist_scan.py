import jax.numpy as jnp
import numpy as np
import pytest

from cme213_tpu.dist import distributed_segmented_scan, make_mesh_1d
from cme213_tpu.ops import head_flags_from_starts
from cme213_tpu.verify import golden


def _case(rng, n, p):
    starts = np.sort(rng.choice(np.arange(1, n), size=p - 1, replace=False))
    s = np.concatenate([[0], starts]).astype(np.int32)
    v = rng.standard_normal(n).astype(np.float32)
    return v, s


@pytest.mark.parametrize("ndev", [2, 4, 8])
def test_matches_single_device_golden(ndev):
    rng = np.random.default_rng(0)
    n = 1024
    v, s = _case(rng, n, 37)
    mesh = make_mesh_1d(ndev)
    flags = head_flags_from_starts(jnp.asarray(s), n)
    out = np.asarray(distributed_segmented_scan(jnp.asarray(v), flags, mesh))
    ref = golden.host_segmented_scan(v, s)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_segment_spanning_many_shards():
    # one giant segment: the scan must thread carries through every shard
    n = 512
    v = np.ones(n, dtype=np.float32)
    s = np.array([0], dtype=np.int32)
    mesh = make_mesh_1d(8)
    flags = head_flags_from_starts(jnp.asarray(s), n)
    out = np.asarray(distributed_segmented_scan(jnp.asarray(v), flags, mesh))
    np.testing.assert_allclose(out, np.arange(1, n + 1, dtype=np.float32))


def test_head_on_shard_boundary():
    n = 64
    mesh = make_mesh_1d(4)
    v = np.ones(n, dtype=np.float32)
    # heads exactly at shard boundaries (16, 32) and mid-shard (40)
    s = np.array([0, 16, 32, 40], dtype=np.int32)
    flags = head_flags_from_starts(jnp.asarray(s), n)
    out = np.asarray(distributed_segmented_scan(jnp.asarray(v), flags, mesh))
    ref = golden.host_segmented_scan(v, s)
    np.testing.assert_allclose(out, ref)


def test_uneven_length_rejected():
    mesh = make_mesh_1d(8)
    v = jnp.ones(100)
    f = jnp.zeros(100, jnp.int32).at[0].set(1)
    with pytest.raises(ValueError):
        distributed_segmented_scan(v, f, mesh)


@pytest.mark.parametrize("mode", ["ring", "gather"])
def test_carry_modes_agree(mode):
    from cme213_tpu.ops import segmented_scan

    mesh = make_mesh_1d(8)
    rng = np.random.default_rng(11)
    n = 8 * 64
    v = rng.standard_normal(n).astype(np.float32)
    starts = np.unique(np.concatenate([[0], rng.integers(1, n, 9)]))
    flags = head_flags_from_starts(jnp.asarray(starts, jnp.int32), n)
    ref = np.asarray(segmented_scan(jnp.asarray(v), flags))
    out = np.asarray(distributed_segmented_scan(
        jnp.asarray(v), flags, mesh, carry_mode=mode))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_carry_mode_rejects_unknown():
    mesh = make_mesh_1d(8)
    v = jnp.ones((16,), jnp.float32)
    f = jnp.zeros((16,), jnp.int32).at[0].set(1)
    with pytest.raises(ValueError):
        distributed_segmented_scan(v, f, mesh, carry_mode="bogus")
