"""Gang supervision — heartbeats, stall detection, supervised-run config.

The reference's failure model is MPI_Abort: any rank dying kills the job,
and a rank *hanging* in a collective kills nothing — the job just stops
making progress until the scheduler's wall-clock limit fires.  PR 2's
launcher closed the first gap (``--max-restarts`` relaunches a dead rank)
but only the blunt whole-job ``--timeout`` caught the second.  This module
closes it properly, TorchElastic-style:

- each rank emits **file-based heartbeats** carrying its current step
  (atomic JSON writes — the same rename discipline as the checkpoint
  layer, so the supervisor never reads a torn beat);
- the launcher-side :class:`GangSupervisor` folds process liveness and
  heartbeat progress into per-rank verdicts, distinguishing "rank exited"
  from "rank alive but its step counter is frozen" (the hung-collective
  signature) — the latter detected after ``--stall-timeout`` seconds
  without step progress;
- either verdict condemns the **whole gang**: ranks blocked in a
  collective with a dead peer cannot make progress, so the launcher kills
  and relaunches all of them and the workload resumes from the last
  committed epoch (``dist/ckpt.py``).

Heartbeats are files (not sockets, not collectives) so supervision keeps
working precisely when the thing being supervised — the collective
runtime — is wedged, and on backends with no multiprocess support at all.
"""

from __future__ import annotations

import json
import os
import time

#: env names the launcher exports to supervised ranks
HEARTBEAT_DIR_ENV = "CME213_HEARTBEAT_DIR"
HEARTBEAT_INTERVAL_ENV = "CME213_HEARTBEAT_INTERVAL"
CKPT_DIR_ENV = "CME213_CKPT_DIR"
CKPT_EVERY_ENV = "CME213_CKPT_EVERY"
RESUME_ENV = "CME213_RESUME"


def heartbeat_path(hb_dir: str, rank: int) -> str:
    return os.path.join(hb_dir, f"rank{int(rank)}.json")


class HeartbeatWriter:
    """Rank-side heartbeat emitter: ``beat(step)`` atomically publishes
    ``{rank, step, pid, incarnation, t}``.  ``interval`` throttles
    same-step re-beats (a step *change* always publishes — progress is the
    signal the supervisor watches)."""

    def __init__(self, hb_dir: str, rank: int, interval: float = 0.0):
        from ..core.faults import incarnation

        self.path = heartbeat_path(hb_dir, rank)
        self.rank = int(rank)
        self.interval = float(interval)
        self.incarnation = incarnation()
        self._last_step: int | None = None
        self._last_t = 0.0
        os.makedirs(hb_dir, exist_ok=True)

    def beat(self, step: int) -> None:
        now = time.time()
        if (self._last_step == step
                and now - self._last_t < self.interval):
            return
        rec = {"rank": self.rank, "step": int(step), "pid": os.getpid(),
               "incarnation": self.incarnation, "t": round(now, 6)}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.path)
        self._last_step = step
        self._last_t = now
        # mirror each *published* beat into the trace so `trace merge`
        # can interleave rank progress with commits and gang verdicts
        # (same throttle as the file write — never chattier than it)
        from ..core.trace import record_event

        record_event("heartbeat", rank=self.rank, step=int(step))


def heartbeat_from_env() -> HeartbeatWriter | None:
    """The supervised-rank entry: a writer wired from the launcher's env,
    or None when this run is not supervised."""
    hb_dir = os.environ.get(HEARTBEAT_DIR_ENV)
    if not hb_dir:
        return None
    rank = int(os.environ.get("JAX_PROCESS_ID", "0"))
    interval = float(os.environ.get(HEARTBEAT_INTERVAL_ENV, "0") or 0)
    return HeartbeatWriter(hb_dir, rank, interval=interval)


def read_heartbeat(hb_dir: str, rank: int) -> dict | None:
    """One rank's latest beat, or None (absent rank / torn-mid-replace
    reads are impossible by construction, but a missing file is normal
    before the first beat)."""
    try:
        with open(heartbeat_path(hb_dir, rank)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def read_all_heartbeats(hb_dir: str) -> dict[int, dict]:
    """Every rank's latest beat in ``hb_dir``, keyed by rank — discovered
    by globbing ``rank*.json`` so callers (``top --hb-dir``) need not know
    the world size.  Unreadable or malformed files are skipped."""
    import glob

    out: dict[int, dict] = {}
    for path in sorted(glob.glob(os.path.join(hb_dir, "rank*.json"))):
        try:
            with open(path) as f:
                beat = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(beat, dict) and isinstance(beat.get("rank"), int):
            out[beat["rank"]] = beat
    return out


def supervised_env_config() -> dict:
    """Checkpoint plumbing the launcher exported for this rank:
    ``{ckpt_dir, ckpt_every, resume}`` (ckpt_dir None when unsupervised)."""
    return {
        "ckpt_dir": os.environ.get(CKPT_DIR_ENV) or None,
        "ckpt_every": int(os.environ.get(CKPT_EVERY_ENV, "0") or 0),
        "resume": os.environ.get(RESUME_ENV, "") not in ("", "0"),
    }


class GangSupervisor:
    """Launcher-side progress tracker for one gang incarnation.

    ``observe(rank, alive)`` per poll; ``stalled()`` lists ranks that are
    alive but whose heartbeat step has not advanced within
    ``stall_timeout`` seconds — counted from gang spawn for ranks that
    never beat at all, so a rank wedged in the coordinator handshake (or
    in its first collective) is caught by the same clock.
    """

    def __init__(self, hb_dir: str, num_ranks: int, stall_timeout: float,
                 clock=None):
        self.hb_dir = hb_dir
        self.num_ranks = int(num_ranks)
        self.stall_timeout = float(stall_timeout)
        # injectable time source (core.resilience.Clock protocol) so stall
        # budgets are testable without wall-clock sleeps
        if clock is None:
            from ..core.resilience import Clock

            clock = Clock()
        self.clock = clock
        self.reset()

    def reset(self) -> None:
        """New gang incarnation: restart every rank's progress clock and
        drop stale beats from the previous incarnation."""
        now = self.clock.now()
        self._progress = {r: (None, now) for r in range(self.num_ranks)}
        for r in range(self.num_ranks):
            try:
                os.unlink(heartbeat_path(self.hb_dir, r))
            except OSError:
                pass

    def step_of(self, rank: int) -> int | None:
        beat = read_heartbeat(self.hb_dir, rank)
        return None if beat is None else beat.get("step")

    def stalled(self) -> list[dict]:
        """Ranks whose step counter is frozen past the stall budget:
        ``[{rank, step, stalled_s}]``."""
        now = self.clock.now()
        out = []
        for rank in range(self.num_ranks):
            step = self.step_of(rank)
            last_step, since = self._progress[rank]
            if step != last_step:  # progress (or first beat): reset clock
                self._progress[rank] = (step, now)
                continue
            if now - since > self.stall_timeout:
                out.append({"rank": rank, "step": step,
                            "stalled_s": round(now - since, 3)})
        return out
