"""Pipelined Pallas heat stencil — the tuned kernel path, v2.

TPU-native analog of the reference's hand-tuned shared-memory stencil
(``gpuShared``, ``hw/hw2/programming/2dHeat.cu:466-515``): where 128×4 CUDA
threads cooperatively staged a 128×32 halo tile into ``__shared__``, here
each Pallas grid step receives a full-width row band in VMEM and emits a
``(tile_y, W)`` output tile.  Unlike ``stencil_pallas.py`` (hand-rolled HBM
DMA + double buffering), this version rides Pallas's *automatic* pipelining:
the halo rows arrive through three input refs of the same array — a
``(Kpad, W)`` band above, the ``(tile_y, W)`` center, and a ``(Kpad, W)``
band below — whose blocks Pallas prefetches and double-buffers for us.
Overlap of DMA and compute therefore comes from the pipeline emitter, not
manual semaphore code, and Mosaic sees simple VMEM refs.

One kernel covers both the plain stencil (``k=1``) and temporal blocking
(``k>1``: k timesteps fused per HBM pass, the arithmetic-intensity
multiplier the 48 KB shared memories of the reference's GPU era could not
hold enough halo for).  Per k-block the band carries ``K = k·border`` extra
rows of halo each side (padded to the 8-row sublane quantum); validity
shrinks by ``border`` rows per sub-step, exactly covering the margin, and
the Dirichlet bands are re-imposed between sub-steps in the reference's
band order (bottom/top rows, then left/right columns overwriting the
corners — ``2dHeat.cu:326-344``).

Shift mechanics: ±border shifts are ``pltpu.roll`` circular rotations of
the whole band.  Lane wrap-around lands in the ≥``gx-border`` column region
(Dirichlet + lane padding), which the masking rewrites every sub-step, so
wrapped values are never observed; sublane wrap lands outside the validity
margin.  Interior results are bitwise-identical to the XLA shifted-slice
path (``ops/stencil.py``) — same coefficients, same accumulation order.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .stencil import BORDER_FOR_ORDER, STENCIL_COEFFS

LANE = 128
SUBLANE = 8


def _ceil_to(n: int, q: int) -> int:
    return -(-n // q) * q


def _roll(u, shift: int, axis: int, interpret: bool):
    if shift == 0:
        return u
    if interpret:  # pltpu.roll has no interpret-mode rule; jnp.roll matches
        return jnp.roll(u, shift, axis)
    return pltpu.roll(u, shift % u.shape[axis], axis)


# (the kernel factory is shared with the shard-local variant: the
# single-device kernel is exactly _make_local_kernel with offs = (0, 0) —
# see its definition below pick_pipeline_tile)


def _apply_substeps(u, rows, cols, order: int, k: int, border: int,
                    ny: int, nx: int,
                    bc: tuple[float, float, float, float],
                    xcfl: float, ycfl: float, interpret: bool):
    """k stencil sub-steps + Dirichlet re-imposition on a halo band.

    The ONE definition of the update both kernel factories share (1-D
    full-width and column-tiled): same taps, same accumulation order,
    same reference band order (rows first, then columns overwrite the
    corners) — the bitwise-equality contract between all kernel forms
    lives here.  ``rows``/``cols`` are global halo-grid coordinate grids;
    conditions ``< border`` / ``>= border + n`` are the physical
    Dirichlet bands.
    """
    b = BORDER_FOR_ORDER[order]
    coeffs = STENCIL_COEFFS[order]
    bc_top, bc_left, bc_bottom, bc_right = bc
    dtype = u.dtype
    for _ in range(k):
        accx = jnp.zeros_like(u)
        accy = jnp.zeros_like(u)
        for kk, c in enumerate(coeffs):
            c = jnp.asarray(c, dtype)
            accx = accx + c * _roll(u, b - kk, 1, interpret)
            accy = accy + c * _roll(u, b - kk, 0, interpret)
        new = (u + jnp.asarray(xcfl, dtype) * accx
               + jnp.asarray(ycfl, dtype) * accy)
        new = jnp.where(rows < border, jnp.asarray(bc_bottom, dtype), new)
        new = jnp.where(rows >= border + ny,
                        jnp.asarray(bc_top, dtype), new)
        new = jnp.where(cols < border, jnp.asarray(bc_left, dtype), new)
        new = jnp.where(cols >= border + nx,
                        jnp.asarray(bc_right, dtype), new)
        u = new
    return u


@partial(jax.jit,
         static_argnames=("order", "iters", "k", "xcfl", "ycfl", "bc",
                          "tile_y", "interpret"),
         donate_argnums=(0,))
def run_heat_pipeline(u: jnp.ndarray, iters: int, order: int, xcfl, ycfl,
                      bc: tuple[float, float, float, float], k: int = 1,
                      tile_y: int = 256,
                      interpret: bool = False) -> jnp.ndarray:
    """``iters`` timesteps of the pipelined Pallas stencil.

    ``u`` is the (gy, gx) halo grid from ``make_initial_grid``; ``bc`` is
    ``SimParams.bc`` = (top, left, bottom, right).  ``iters`` must divide
    by ``k``.  ``tile_y`` must be a multiple of the halo band height
    ``Kpad = ceil8(k·border)`` (so the halo refs index on block boundaries).
    Returns the full (gy, gx) halo grid after ``iters`` steps, bitwise
    equal on the interior to ``run_heat``.
    """
    b = BORDER_FOR_ORDER[order]
    K = k * b
    kpad = _ceil_to(K, SUBLANE)
    gy, gx = u.shape
    if iters % k != 0:
        raise ValueError(f"iters={iters} must divide by k={k}")
    assert tile_y % kpad == 0, "tile_y must divide by ceil8(k*border)"
    W = _ceil_to(gx, LANE)
    GY = _ceil_to(gy, tile_y)
    # x-roll wrap safety needs W - gx + b >= b, i.e. wrapped lanes land in
    # the [gx - b, W) region the masking rewrites every sub-step — always
    # true since W >= gx, no matter how the lane padding falls
    bc_top, bc_left, bc_bottom, bc_right = bc

    # pad columns with bc_right and rows with bc_top: the padding then holds
    # exactly the values the in-kernel masking rewrites, so it is a fixed
    # point of the iteration and the [0:gy, 0:gx] corner is undisturbed
    padded = u
    if W != gx:
        padded = jnp.pad(padded, ((0, 0), (0, W - gx)),
                         constant_values=bc_right)
    if GY != gy:
        padded = jnp.pad(padded, ((0, GY - gy), (0, 0)),
                         constant_values=bc_top)

    nblk = GY // tile_y
    t_per_k = tile_y // kpad  # halo-block indices per center block
    # the single-device kernel is the shard-local kernel at offset (0, 0):
    # the grid's BC/padding bands sit at global rows < b / >= b + ny (and
    # the matching column conditions), which the masking rewrites every
    # sub-step — keeping the padding a fixed point of the iteration
    kernel = _make_local_kernel(order, k, tile_y, kpad, gy - 2 * b,
                                gx - 2 * b, b, bc, float(xcfl),
                                float(ycfl), interpret)
    offs = jnp.zeros((2,), jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((kpad, W),
                         lambda i, offs: (jnp.maximum(i * t_per_k - 1, 0),
                                          0)),
            pl.BlockSpec((tile_y, W), lambda i, offs: (i, 0)),
            pl.BlockSpec((kpad, W),
                         lambda i, offs: (jnp.minimum((i + 1) * t_per_k,
                                                      GY // kpad - 1), 0)),
        ],
        out_specs=pl.BlockSpec((tile_y, W), lambda i, offs: (i, 0)),
    )
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((GY, W), u.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )

    def body(_, p):
        return call(offs, p, p, p)

    padded = lax.fori_loop(0, iters // k, body, padded)
    return padded[:gy, :gx]


# conservative per-core VMEM budget for the double-buffered band layout:
# the core has ~16 MiB; leave headroom for scratch, constants and the
# scalar-prefetch machinery.  (Empirically the round-3 remote-compile
# crash boundary sits at the 16 MiB line: W=4096 x tile_y=256 needs
# 16.5 MiB and crashes, W=3584 needs 14.4 MiB and compiles.)
VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def pick_pipeline_tile(gy: int, k: int, order: int, target: int = 256,
                       width: int | None = None,
                       dtype_bytes: int = 4) -> int:
    """A tile_y that is a multiple of Kpad and keeps the band in VMEM.

    With ``width`` (the raw grid width ``gx``; lane-padded internally to
    the kernel's W) given, the choice is
    clamped so the kernel's double-buffered VMEM footprint —
    ``2 * dtype_bytes * W * (2*tile_y + 2*kpad)`` for the center+halo
    inputs and the output block — stays under ``VMEM_BUDGET_BYTES``,
    so a known-over-budget tile is never even offered to the compiler
    (a crashed remote compile can wedge the tunnel for every later
    kernel, the BENCH_r02 failure mode).  An explicit
    ``CME213_MEMORY_BUDGET`` below the VMEM budget clamps further — the
    admission-control knob (``core/admission.py``) reaches the tile
    choice the same way it reaches solve chunk sizes.
    """
    b = BORDER_FOR_ORDER[order]
    kpad = _ceil_to(k * b, SUBLANE)
    t = max(_ceil_to(min(target, gy), kpad), kpad)
    if width is not None:
        from ..core import admission

        budget = VMEM_BUDGET_BYTES
        configured = admission.memory_budget()
        if configured is not None:
            budget = min(budget, configured)
        W = _ceil_to(width, LANE)

        def footprint(ty: int) -> int:
            return 2 * dtype_bytes * W * (2 * ty + 2 * kpad)

        while t > kpad and footprint(t) > budget:
            t -= kpad
    return t


#: canonical conformance-probe state: the nonuniform-interior +
#: distinct-BC configuration that empirically maximizes rounding-path
#: coverage (the shape the bitwise pin tests use)
_PROBE_BC = (1.5, 0.5, 2.0, 0.25)


def _conformance_probe_grid(order: int):
    """(params, u0) small canonical probe: gradient interior, distinct
    Dirichlet values on all four sides."""
    import numpy as np

    from ..config import SimParams
    from ..grid import make_initial_grid

    p = SimParams(nx=44, ny=40, order=order, iters=1, bc_top=_PROBE_BC[0],
                  bc_left=_PROBE_BC[1], bc_bottom=_PROBE_BC[2],
                  bc_right=_PROBE_BC[3])
    u0 = np.array(make_initial_grid(p, dtype=jnp.float32))
    b = BORDER_FOR_ORDER[order]
    u0[b:-b, b:-b] += np.linspace(0, 1, p.ny * p.nx,
                                  dtype=np.float32).reshape(p.ny, p.nx)
    return p, u0


def _heat_conformance_gate(order: int, k: int, tile_x: int, interpret: bool):
    """``gate(rung) -> bool`` for the heat ladder: first use of a Pallas
    rung (per process × order × k) runs the canonical probe through that
    rung and the XLA reference, bitwise — the kernel-equality contract.
    On this repo's known divergence axes (order 8 / temporal blocking,
    see docs/resilience.md "Guarded execution") the probe fails and the
    gate keeps those rungs out of the serving ladder."""
    import numpy as np

    from ..core import conformance, programs
    from .stencil import run_heat

    def gate(rung: str) -> bool:
        if rung == "xla":
            return True  # the reference rung needs no probe
        p, u0 = _conformance_probe_grid(order)
        iters = 4 * k
        ty = pick_pipeline_tile(u0.shape[0], k, order, target=64,
                                width=u0.shape[1])
        sc = f"{u0.shape[0]}x{u0.shape[1]}/order{order}/k{k}"

        def probe_program(r, build):
            # probes compile THROUGH the program cache (same key layout
            # as the dispatch path) so gating a rung also warms its
            # probe-class program instead of paying a discarded compile
            return programs.get(
                "heat", r, sc, build, dtype="float32",
                warm=lambda fn: fn(jnp.array(u0)),
                iters=iters, xcfl=p.xcfl, ycfl=p.ycfl, bc=p.bc, k=k,
                tile_y=ty, tile_x=tile_x, interpret=interpret)

        def candidate():
            if rung == "pipeline":
                fn = probe_program(rung, lambda: lambda v:
                                   run_heat_pipeline(v, iters, order, p.xcfl,
                                                     p.ycfl, p.bc, k=k,
                                                     tile_y=ty,
                                                     interpret=interpret))
            else:
                fn = probe_program(rung, lambda: lambda v:
                                   run_heat_pipeline2d(v, iters, order,
                                                       p.xcfl, p.ycfl, p.bc,
                                                       k=k, tile_y=ty,
                                                       tile_x=tile_x,
                                                       interpret=interpret))
            return np.asarray(fn(jnp.array(u0)))

        def reference():
            fn = probe_program("xla", lambda: lambda v:
                               run_heat(v, iters, order, p.xcfl, p.ycfl))
            return np.asarray(fn(jnp.array(u0)))

        return conformance.check("heat", rung,
                                 shape_class=f"order{order}/k{k}",
                                 candidate=candidate, reference=reference).ok

    return gate


def run_heat_resilient(u, iters: int, order: int, xcfl, ycfl,
                       bc: tuple[float, float, float, float], k: int = 1,
                       tile_y: int | None = None, tile_x: int | None = None,
                       interpret: bool = False, timer=None,
                       phase_label: str = "gpu computation shared",
                       conformance: bool = True):
    """Heat stencil behind the kernel fallback ladder: pipelined Pallas
    (1-D full-width band) → column-tiled Pallas → XLA fused slices.

    A rung that fails to lower or run — a Mosaic crash at an untested
    (width, tile) cell, a preempted backend, or an injected
    ``CME213_FAULTS=fail:heat.pipeline`` — demotes to the next instead of
    aborting the solve; every kernel form is bitwise-equal on the
    interior, so a demoted run returns the same grid.  That equality
    contract is *enforced*, not assumed: with ``conformance`` (default),
    each Pallas rung's first use per process × (order, k) runs a small
    bitwise probe against the XLA reference and a diverging rung is
    demoted with ``WRONG_ANSWER`` before it can serve
    (``core/conformance.py``; steady state is one dict lookup).

    Per rung: untimed warmup behind a named ``check_op`` barrier
    (failures surface there, attributed), then the timed run under
    ``timer``/``phase_label``.  A Pallas rung that dies
    ``RESOURCE_EXHAUSTED`` (real, or ``CME213_FAULTS=oom:heat.pipeline``)
    **halves its tile_y** (down to the halo quantum) and retries before
    demoting — the admission-control response applied to the tile knob,
    with each halving recorded as a ``chunk-shrunk`` event.

    Returns a ``FallbackResult`` whose ``.value`` is the solved grid and
    ``.rung`` the kernel that actually served; demotions are recorded as
    structured ``rung-failed``/``served`` trace events.  The ladder
    bookkeeping is host-side and pre-dispatch — with no faults installed
    and a healthy first rung, the timed region is identical to calling
    ``run_heat_pipeline`` directly.
    """
    import jax.numpy as jnp

    from ..core import (PhaseTimer, check_op, metrics, programs, span,
                        with_fallback)
    from ..core.faults import maybe_oom
    from ..core.resilience import FailureKind, classify_failure
    from ..core.trace import record_event
    from .stencil import run_heat

    b = BORDER_FOR_ORDER[order]
    kpad = _ceil_to(k * b, SUBLANE)
    gy, gx = u.shape
    shape_class = f"{gy}x{gx}/order{order}/k{k}"
    if tile_y is None or tile_x is None:
        # tile knobs the caller left open resolve tuned-or-default
        # (core/tune.py, keyed by this shape class); an empty cache or
        # CME213_TUNE=0 leaves pick_pipeline_tile/512 in charge
        from ..core import tune

        # only the knobs the caller left open are declared, so a tuned
        # entry can never stomp an explicitly pinned tile
        open_knobs = {kn: None for kn, v in
                      (("tile_y", tile_y), ("tile_x", tile_x)) if v is None}
        t = tune.resolve("heat", shape_class, str(u.dtype), **open_knobs)
        tile_y = t.get("tile_y", tile_y)
        tile_x = t.get("tile_x", tile_x)
    tile_x = tile_x or 512
    ty = tile_y or pick_pipeline_tile(gy, k, order, width=gx)
    timer = timer or PhaseTimer()
    u_host = jax.device_get(u)  # rungs donate; each attempt re-uploads
    from ..core.roofline import heat_cost

    cost = heat_cost(gy, gx, order=order, iters=iters, dtype=u_host.dtype)

    def timed(rung, runner_at_tile, shrinkable=True):
        # runner_at_tile(ty)(v): the tile knob stays adjustable so a
        # RESOURCE failure can halve it and retry within the rung
        def attempt(ty_cur):
            maybe_oom(f"heat.{rung}")
            # the program comes from the process-wide cache: a miss
            # builds + warms inside the heat.compile span (compile vs run
            # split per rung, like spmv_scan's dispatch — feeding the
            # per-shape-class histograms + retrace detector); a hit skips
            # both, so a repeated solve on a known shape class performs
            # zero retraces.  A halved tile is a new static key — the
            # shrunk retry legitimately recompiles.
            runner = programs.get(
                "heat", rung, shape_class,
                lambda: runner_at_tile(ty_cur), dtype=str(u_host.dtype),
                warm=lambda fn: check_op(f"heat.{rung}",
                                         fn(jnp.array(u_host))),
                cost=cost, probe=lambda: (jnp.array(u_host),),
                iters=iters, xcfl=xcfl, ycfl=ycfl, bc=bc, k=k,
                tile_y=ty_cur, tile_x=tile_x, interpret=interpret)
            with span("heat.run", kernel=rung, size=gy, iters=iters,
                      shape_class=shape_class) as sp:
                sp.roofline(cost.nbytes, cost.flops)
                with timer.phase(phase_label) as ph:
                    out = runner(jnp.array(u_host))
                    ph.block(out)
            return out

        def thunk():
            ty_cur = ty
            while True:
                try:
                    return attempt(ty_cur)
                except Exception as e:  # noqa: BLE001 — classify first
                    if (not shrinkable or ty_cur <= kpad
                            or classify_failure(e)
                            is not FailureKind.RESOURCE):
                        raise
                    ty_new = max(kpad, _ceil_to(ty_cur // 2, kpad))
                    if ty_new >= ty_cur:
                        raise
                    metrics.counter("admission.chunk_shrunk").inc()
                    record_event("chunk-shrunk", op=f"heat.{rung}",
                                 from_size=ty_cur, to_size=ty_new,
                                 reason=type(e).__name__)
                    ty_cur = ty_new
        return thunk

    ladder = [("pipeline", timed("pipeline", lambda t: lambda v:
              run_heat_pipeline(v, iters, order, xcfl, ycfl, bc, k=k,
                                tile_y=t, interpret=interpret)))]
    if k * b <= LANE:  # the column-tiled form's side-halo limit
        ladder.append(("pipeline2d", timed(
            "pipeline2d", lambda t: lambda v: run_heat_pipeline2d(
                v, iters, order, xcfl, ycfl, bc, k=k, tile_y=t,
                tile_x=tile_x, interpret=interpret))))
    ladder.append(("xla", timed(
        "xla", lambda t: lambda v: run_heat(v, iters, order, xcfl, ycfl),
        shrinkable=False)))
    gate = (_heat_conformance_gate(order, k, tile_x, interpret)
            if conformance else None)
    return with_fallback("heat", ladder, gate=gate)


def _make_tiled_kernel(order: int, k: int, tile_y: int, tile_x: int,
                       kpad: int, ny: int, nx: int, border: int,
                       bc: tuple[float, float, float, float],
                       xcfl: float, ycfl: float, interpret: bool):
    """Column-tiled variant: output tiles are (tile_y, tile_x) and the halo
    arrives through a 3×3 ref layout — (kpad)-row bands above/below,
    128-lane bands left/right, and the four corners (the k-step dependency
    cone is an L1 ball, so diagonal data IS needed for k ≥ 2).  All
    concatenations are 8/128-aligned; x-roll wrap lands in the 128-lane
    side margins (K ≤ 128 asserted by the caller)."""

    def kernel(offs, tl, t, tr, l, m, r, bl, bo, br, out_ref):
        i = pl.program_id(0)
        j = pl.program_id(1)
        band = jnp.concatenate([
            jnp.concatenate([tl[:], t[:], tr[:]], axis=1),
            jnp.concatenate([l[:], m[:], r[:]], axis=1),
            jnp.concatenate([bl[:], bo[:], br[:]], axis=1),
        ], axis=0)
        H, W = band.shape
        rows = (jax.lax.broadcasted_iota(jnp.int32, (H, W), 0)
                + i * tile_y - kpad + offs[0])
        cols = (jax.lax.broadcasted_iota(jnp.int32, (H, W), 1)
                + j * tile_x - LANE + offs[1])
        u = _apply_substeps(band, rows, cols, order, k, border, ny, nx, bc,
                            xcfl, ycfl, interpret)
        out = _roll(u, -kpad, 0, interpret)[:tile_y, :]
        out_ref[:] = _roll(out, -LANE, 1, interpret)[:, :tile_x]

    return kernel


@partial(jax.jit,
         static_argnames=("order", "iters", "k", "xcfl", "ycfl", "bc",
                          "tile_y", "tile_x", "interpret"),
         donate_argnums=(0,))
def run_heat_pipeline2d(u: jnp.ndarray, iters: int, order: int, xcfl, ycfl,
                        bc: tuple[float, float, float, float], k: int = 1,
                        tile_y: int = 256, tile_x: int = 512,
                        interpret: bool = False) -> jnp.ndarray:
    """Column-tiled form of ``run_heat_pipeline`` (2-D grid of
    (tile_y, tile_x) output tiles).  Semantically identical — bitwise
    equal to ``run_heat`` on the interior; exists because full-width
    bands at large widths are the suspected trigger of the remote-compile
    crash, and because smaller output tiles pipeline at finer grain.
    ``tile_x`` must divide by 128; ``k·border`` must be ≤ 128 (the x-halo
    the side refs carry).
    """
    b = BORDER_FOR_ORDER[order]
    K = k * b
    kpad = _ceil_to(K, SUBLANE)
    gy, gx = u.shape
    if iters % k != 0:
        raise ValueError(f"iters={iters} must divide by k={k}")
    assert tile_y % kpad == 0, "tile_y must divide by ceil8(k*border)"
    assert tile_x % LANE == 0, "tile_x must divide by 128"
    assert K <= LANE, "k*border exceeds the 128-lane side halo"
    bc_top, bc_left, bc_bottom, bc_right = bc

    GX = _ceil_to(gx, tile_x)
    GY = _ceil_to(gy, tile_y)
    padded = u
    if GX != gx:
        padded = jnp.pad(padded, ((0, 0), (0, GX - gx)),
                         constant_values=bc_right)
    if GY != gy:
        padded = jnp.pad(padded, ((0, GY - gy), (0, 0)),
                         constant_values=bc_top)

    ty = tile_y // kpad
    tx = tile_x // LANE
    GYk = GY // kpad
    GX128 = GX // LANE
    kernel = _make_tiled_kernel(order, k, tile_y, tile_x, kpad, gy - 2 * b,
                                gx - 2 * b, b, bc, float(xcfl),
                                float(ycfl), interpret)
    offs = jnp.zeros((2,), jnp.int32)

    def iT(i, j, offs):
        return jnp.maximum(i * ty - 1, 0)

    def iB(i, j, offs):
        return jnp.minimum((i + 1) * ty, GYk - 1)

    def jL(i, j, offs):
        return jnp.maximum(j * tx - 1, 0)

    def jR(i, j, offs):
        return jnp.minimum((j + 1) * tx, GX128 - 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(GY // tile_y, GX // tile_x),
        in_specs=[
            pl.BlockSpec((kpad, LANE),
                         lambda i, j, offs: (iT(i, j, offs), jL(i, j, offs))),
            pl.BlockSpec((kpad, tile_x),
                         lambda i, j, offs: (iT(i, j, offs), j)),
            pl.BlockSpec((kpad, LANE),
                         lambda i, j, offs: (iT(i, j, offs), jR(i, j, offs))),
            pl.BlockSpec((tile_y, LANE),
                         lambda i, j, offs: (i, jL(i, j, offs))),
            pl.BlockSpec((tile_y, tile_x), lambda i, j, offs: (i, j)),
            pl.BlockSpec((tile_y, LANE),
                         lambda i, j, offs: (i, jR(i, j, offs))),
            pl.BlockSpec((kpad, LANE),
                         lambda i, j, offs: (iB(i, j, offs), jL(i, j, offs))),
            pl.BlockSpec((kpad, tile_x),
                         lambda i, j, offs: (iB(i, j, offs), j)),
            pl.BlockSpec((kpad, LANE),
                         lambda i, j, offs: (iB(i, j, offs), jR(i, j, offs))),
        ],
        out_specs=pl.BlockSpec((tile_y, tile_x), lambda i, j, offs: (i, j)),
    )
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((GY, GX), u.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )

    def body(_, p):
        return call(offs, p, p, p, p, p, p, p, p, p)

    padded = lax.fori_loop(0, iters // k, body, padded)
    return padded[:gy, :gx]


def _make_local_kernel(order: int, k: int, tile_y: int, kpad: int,
                       ny: int, nx: int, border: int,
                       bc: tuple[float, float, float, float],
                       xcfl: float, ycfl: float, interpret: bool):
    """Shard-local variant: BC masking keyed on per-shard GLOBAL halo-grid
    coordinates delivered via scalar prefetch (``offs = [gy0, gx0]``, the
    coords of array element [0, 0]).  For interior shards no mask ever
    fires and the kernel is pure stencil; boundary shards re-impose the
    same Dirichlet bands the single-device kernel does."""

    def kernel(offs, top_ref, mid_ref, bot_ref, out_ref):
        i = pl.program_id(0)
        band = jnp.concatenate([top_ref[:], mid_ref[:], bot_ref[:]], axis=0)
        H, W = band.shape
        # global-coordinate grids; conditions < b / >= b + n are the same
        # physical-Dirichlet-band tests the sharded XLA path uses
        # (dist/heat._multistep_local_step)
        rows = (jax.lax.broadcasted_iota(jnp.int32, (H, W), 0)
                + i * tile_y - kpad + offs[0])
        cols = jax.lax.broadcasted_iota(jnp.int32, (H, W), 1) + offs[1]
        u = _apply_substeps(band, rows, cols, order, k, border, ny, nx, bc,
                            xcfl, ycfl, interpret)
        out_ref[:] = _roll(u, -kpad, 0, interpret)[:tile_y, :]

    return kernel


def stencil_local_multistep(p: jnp.ndarray, gy0, gx0, ny: int, nx: int,
                            order: int, xcfl: float, ycfl: float,
                            bc: tuple[float, float, float, float],
                            k: int = 1, tile_y: int = 128,
                            interpret: bool = False) -> jnp.ndarray:
    """k fused timesteps on a K-padded shard-local block (Pallas).

    ``p`` is the local block with K = k·border of halo on every side
    (neighbor data or BC fill — what ``dist/heat._assemble_padded``
    produces); ``(gy0, gx0)`` are the global halo-grid coordinates of
    ``p[0, 0]`` (traced values — ``axis_index`` products); ``(ny, nx)``
    the global interior extents.  Returns the updated (H, W) block whose
    rows/cols ``[K, K + local)`` are the valid k-step result — bitwise
    equal to k applications of the sharded XLA path.

    Row/lane padding added here for tiling is sound without masking: the
    appended garbage sits ≥ K away from the valid region, and k sub-steps
    spread garbage by exactly K — reaching, never entering, the valid
    window (same argument as the single-device kernel's clamped edges).
    """
    b = BORDER_FOR_ORDER[order]
    K = k * b
    kpad = _ceil_to(K, SUBLANE)
    assert tile_y % kpad == 0
    H, W = p.shape
    Hp = _ceil_to(H, tile_y)
    Wp = _ceil_to(W, LANE)
    if Hp != H or Wp != W:
        p = jnp.pad(p, ((0, Hp - H), (0, Wp - W)))
    nblk = Hp // tile_y
    t_per_k = tile_y // kpad
    kernel = _make_local_kernel(order, k, tile_y, kpad, ny, nx, b, bc,
                                float(xcfl), float(ycfl), interpret)
    offs = jnp.asarray([gy0, gx0], jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nblk,),
        in_specs=[
            pl.BlockSpec((kpad, Wp),
                         lambda i, offs: (jnp.maximum(i * t_per_k - 1, 0),
                                          0)),
            pl.BlockSpec((tile_y, Wp), lambda i, offs: (i, 0)),
            pl.BlockSpec((kpad, Wp),
                         lambda i, offs: (jnp.minimum((i + 1) * t_per_k,
                                                      Hp // kpad - 1), 0)),
        ],
        out_specs=pl.BlockSpec((tile_y, Wp), lambda i, offs: (i, 0)),
    )
    # inside shard_map the output aval must carry the varying-across-mesh
    # annotation; inherit it from the input block.  jax 0.4.x has neither
    # jax.typeof nor a vma kwarg on ShapeDtypeStruct (its shard_map uses
    # check_rep, with no per-aval annotation) — fall back to a plain struct.
    try:
        vma = jax.typeof(p).vma
        out_shape = jax.ShapeDtypeStruct((Hp, Wp), p.dtype, vma=vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct((Hp, Wp), p.dtype)
    out = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid_spec=grid_spec,
        interpret=interpret,
    )(offs, p, p, p)
    return out[:H, :W]
