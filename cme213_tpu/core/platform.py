"""Force a virtual n-device CPU mesh — the shared platform-defense recipe.

Distributed code is validated on a fake host mesh (the TPU analog of the
reference's "compare N-rank vs 1-rank" methodology, hw5 handout §5.1), which
requires two order-sensitive steps:

1. ``--xla_force_host_platform_device_count=n`` must be in ``XLA_FLAGS``
   *before* the CPU client is created (the flag is read at client init).
2. The platform must be forced to CPU via ``jax.config`` *after* importing
   jax, because this environment's sitecustomize re-forces its own platform
   list at interpreter startup — the ``JAX_PLATFORMS`` env var alone is
   overridden.

Used by both ``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip``
so the incantation can't drift between them.
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def apply_platform_env() -> None:
    """Honor an explicit ``JAX_PLATFORMS`` env var.

    This environment's sitecustomize re-forces its own platform list at
    interpreter startup, so the env var alone is silently overridden — and
    a wedged TPU tunnel then hangs ``jax.devices()`` even for runs that
    asked for CPU.  Re-applying the value through ``jax.config`` (before
    any backend client exists) restores the standard env-var semantics.
    No-op when ``JAX_PLATFORMS`` is unset.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)


def enable_compile_cache(path: str | None = None) -> None:
    """Persistent XLA compilation cache across processes and windows.

    The bench runs every kernel in its own child process, and the capture
    watcher re-runs the whole sequence across tunnel windows — without a
    persistent cache each retry pays the full device compile again (the
    Pallas pipeline kernels take minutes at 4000²; round-5 saw a 15-minute
    window consumed by one cold compile).  With the cache, a kernel
    compiled in any earlier window or child loads back in milliseconds.

    By default the cache is TPU-only: implicit-CPU runs (tests, fake-mesh
    rehearsals) are compile-cheap and would just churn the default cache
    dir.  An **explicit** opt-in — ``path`` or the ``CME213_COMPILE_CACHE``
    env var — enables it on any platform, which is the warm-start path:
    ``python -m cme213_tpu serve warmup`` pre-compiles the canonical
    serving buckets into the dir, and a later process start loads every
    known shape class from disk instead of compiling it fresh.  On CPU
    the min-compile-time floor drops to 0 so the sub-second CPU compiles
    actually persist.
    """
    explicit = path or os.environ.get("CME213_COMPILE_CACHE")
    on_cpu = os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
    if on_cpu and not explicit:
        return
    import jax

    cache_dir = explicit or os.path.join(
        os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
        ".jax_compile_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.0 if on_cpu else 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # older jax without these flags — cache optional,
        # but a silent miss re-opens the cold-compile-per-window cost, so say so
        import sys

        print(f"warning: persistent compile cache disabled ({e})",
              file=sys.stderr)


def device_preflight(seconds: float = 90.0) -> bool:
    """True iff a trivial device op completes within ``seconds``.

    A wedged TPU tunnel hangs inside PJRT client creation, where Python
    signal handlers can't fire — so the probe runs on a daemon thread and
    the caller just times out.  The shared failure-detection primitive
    behind ``bench.py``'s per-kernel preflight and the probe scripts
    (the reference's fail-fast `check_launch`, aimed at a failure mode
    GPUs didn't have).
    """
    import threading

    from .faults import maybe_unreachable
    if maybe_unreachable("device.preflight"):
        return False

    done = threading.Event()
    ok = [False]

    def probe():
        # done.set() in finally: a backend that ERRORS instantly (bad
        # platform name, refused connection) reports False immediately
        # instead of burning the whole budget; only a true hang waits it
        try:
            apply_platform_env()
            import jax
            import jax.numpy as jnp

            (jnp.ones((8, 8)) * 2).block_until_ready()
            ok[0] = True
        finally:
            done.set()

    threading.Thread(target=probe, daemon=True).start()
    return done.wait(seconds) and ok[0]


def force_cpu_devices(n_devices: int) -> None:
    """Pin JAX to the CPU platform with at least ``n_devices`` host devices.

    Safe to call more than once; an existing smaller device-count flag is
    raised to ``n_devices``.  Fails loudly if the CPU client was already
    created with too few devices (the flag can no longer take effect).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{_FLAG}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = f"{flags} {_FLAG}={n_devices}".strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(
            m.group(0), f"{_FLAG}={n_devices}")

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        # CPU test/rehearsal compiles are cheap; don't churn the TPU
        # compile cache (enabled at package import) with their entries —
        # unless the operator explicitly asked for a cache dir (the
        # warm-start opt-in), which wins
        if not os.environ.get("CME213_COMPILE_CACHE"):
            jax.config.update("jax_compilation_cache_dir", None)
    except Exception as e:
        import sys

        print(f"warning: could not disable compile cache for CPU run ({e})",
              file=sys.stderr)

    devs = jax.devices()
    if devs[0].platform != "cpu" or len(devs) < n_devices:
        raise RuntimeError(
            f"expected >= {n_devices} CPU devices, have {len(devs)} "
            f"{devs[0].platform!r} device(s) — the XLA backend was "
            "initialized before force_cpu_devices() could take effect "
            "(jax.config platform updates are no-ops once a client "
            "exists); call it before any other jax device use in the "
            "process.")
