import numpy as np
import pytest

native = pytest.importorskip("cme213_tpu.native")


@pytest.fixture(scope="module", autouse=True)
def built():
    try:
        from cme213_tpu.native.build import build_library

        build_library()
    except Exception as e:  # toolchain missing
        pytest.skip(f"native build unavailable: {e}")


@pytest.mark.parametrize("n", [0, 1, 100, 10_000, 1_000_003])
def test_merge_sort(n):
    rng = np.random.default_rng(n or 7)
    x = rng.integers(-(2**31), 2**31, size=n, dtype=np.int64).astype(np.int32)
    ref = np.sort(x)
    out = native.merge_sort(x.copy())
    np.testing.assert_array_equal(out, ref)


def test_merge_sort_thresholds():
    rng = np.random.default_rng(1)
    x = rng.integers(0, 1000, size=50_000).astype(np.int32)
    ref = np.sort(x)
    for st, mt in [(64, 64), (1024, 333), (100_000, 100_000)]:
        np.testing.assert_array_equal(
            native.merge_sort(x.copy(), st, mt), ref)


@pytest.mark.parametrize("n", [0, 1, 257, 100_000])
@pytest.mark.parametrize("num_bits", [4, 8, 11])
def test_radix_sort(n, num_bits):
    rng = np.random.default_rng(n + num_bits)
    x = rng.integers(0, 2**32, size=n, dtype=np.uint64).astype(np.uint32)
    ref = np.sort(x)
    np.testing.assert_array_equal(native.radix_sort(x.copy(), num_bits), ref)


def test_radix_sort_serial_matches_parallel():
    rng = np.random.default_rng(9)
    x = rng.integers(0, 2**32, size=65_537, dtype=np.uint64).astype(np.uint32)
    a = native.radix_sort(x.copy())
    b = native.radix_sort_serial(x.copy())
    np.testing.assert_array_equal(a, b)


def test_thread_control():
    native.set_threads(2)
    assert native.thread_count() == 2
    native.set_threads(4)
    assert native.thread_count() == 4


def test_radix_sort_16bit_large():
    """num_bits=16 (2^16 buckets) on a few million elements — exercises the
    bucket-major scan + scatter-cursor path at its widest setting."""
    rng = np.random.default_rng(16)
    x = rng.integers(0, 2**32, size=3_000_000, dtype=np.uint64).astype(np.uint32)
    ref = np.sort(x)
    out = native.radix_sort(x.copy(), num_bits=16)
    np.testing.assert_array_equal(out, ref)


def _random_problem(rng, n=5000, p=64, q=40):
    starts = np.sort(rng.choice(np.arange(1, n), size=p - 1, replace=False))
    s = np.concatenate([[0], starts]).astype(np.int32)
    a = rng.standard_normal(n).astype(np.float32)
    xx = rng.uniform(-1, 1, n).astype(np.float32)
    return a, s, xx


def test_spmv_scan_cpu_matches_golden():
    """OpenMP CPU SpMV-scan is bitwise-equal to the serial numpy golden
    (same f32 serial accumulation order per segment)."""
    from cme213_tpu.verify import golden

    rng = np.random.default_rng(0)
    a, s, xx = _random_problem(rng)
    for iters in (1, 7):
        ref = golden.host_spmv_scan(a, s, xx, iters)
        out = native.spmv_scan_cpu(a, s, xx, iters)
        np.testing.assert_array_equal(out, ref)


def test_spmv_scan_cpu_thread_invariant():
    """Per-segment scans are serial, so results are bitwise thread-count
    independent (the property that makes the 4-thread table comparable)."""
    rng = np.random.default_rng(1)
    a, s, xx = _random_problem(rng, n=20_000, p=37)
    prev = native.thread_count()
    try:
        native.set_threads(1)
        r1 = native.spmv_scan_cpu(a, s, xx, 5)
        native.set_threads(4)
        r4 = native.spmv_scan_cpu(a, s, xx, 5)
    finally:
        native.set_threads(prev)
    np.testing.assert_array_equal(r1, r4)
    assert not np.array_equal(r1, a)  # it actually did something


def test_spmv_scan_cpu_single_segment():
    rng = np.random.default_rng(2)
    a = rng.standard_normal(100).astype(np.float32)
    xx = np.ones(100, np.float32)
    out = native.spmv_scan_cpu(a, np.array([0], np.int32), xx, 1)
    np.testing.assert_allclose(out, np.cumsum(a, dtype=np.float32), rtol=1e-6)
