#!/bin/bash
# First-tranche device capture: bank the three highest-value measurements
# and git-commit them BEFORE anything long-running touches the chip.  A
# 3-minute tunnel window (round 3 got exactly that) must still leave
# committed device rows behind.
#
#   bash scripts/tpu_tranche1.sh [outdir]
#
# Tranche contents, in order of value:
#   1. headline `xla` kernel at 4000^2 order-8 f32 (replaces the stale
#      round-2 number that had the H2D upload inside the timed region)
#   2. one tuned `pipeline-k4` point at the same shape (the first-ever
#      hardware number for a tuned kernel, if it lands)
#   3. the H2D/D2H transfer sweep (quick, and contextualizes 1-2)
#
# Resumable: a banked row is not re-measured.  A row that failed WITHOUT a
# device signature is conclusive evidence (a compile bug is a result) and
# is not retried; a device-tagged failure is retried next window.  Exit 0
# = the xla row holds a real number and the pipeline row is conclusive.
set -u
cd "$(dirname "$0")/.."
. scripts/capture_lib.sh
OUT="${1:-bench_results}"
mkdir -p "$OUT"

for k in xla pipeline-k4; do
  f="$OUT/tranche1_${k}.json"
  # the headline xla row is only "banked" once it holds a real number —
  # a sticky host-side failure there is re-measured every window (cheap,
  # one child run) instead of wedging the watcher; the pipeline row keeps
  # a sticky failure as conclusive evidence (a compile bug is a result)
  if [ "$k" = xla ] && row_ok "$f"; then
    echo "-- tranche1 $k: already banked"
    continue
  elif [ "$k" != xla ] && row_conclusive "$f"; then
    echo "-- tranche1 $k: already banked"
    continue
  fi
  echo "-- tranche1 $k"
  # the pipeline row pins tile_y=64 — the tile tranche-1 PROVED on
  # device (tile 128 crashed Mosaic at k=4 width 4000 on 2026-07-31;
  # 64 compiled and measured 251.8 GB/s).  A compiler crash kills the
  # child before its own tile ladder can fall back, so the tranche must
  # open with a tile that is known to compile; the pipeline_tune sweep
  # still explores the larger tiles.
  tile_env=""
  [ "$k" = "pipeline-k4" ] && tile_env="BENCH_TILE_Y=64"
  env $tile_env timeout 900 python bench.py --run-measurement \
      --kernel="$k" > "$f.tmp" 2>>"$OUT/tranche1.stderr.log"
  rc=$?
  # child stdout is one JSON row; no row means the process died before
  # reporting.  Classify by exit code: preflight watchdog (42) and
  # timeout kill (124) are device-shaped and retried next window; any
  # other silent death (compiler-helper crash, OOM kill) is recorded as
  # a sticky result so the watcher doesn't re-crash it every window.
  grep '^{' "$f.tmp" | tail -n 1 > "$f" || true
  rm -f "$f.tmp"
  if [ ! -s "$f" ]; then
    if [ "$rc" = 42 ] || [ "$rc" = 124 ]; then
      echo '{"kernel": "'"$k"'", "ok": false,' \
        '"error": "preflight: device unreachable (rc='"$rc"')"}' > "$f"
    else
      echo '{"kernel": "'"$k"'", "ok": false,' \
        '"error": "child exit '"$rc"' with no row (compiler crash?)"}' \
        > "$f"
    fi
  fi
  cat "$f"
done

if [ -s "$OUT/transfer_bandwidth.csv" ]; then
  echo "-- tranche1 transfer sweep: already captured"
else
  echo "-- tranche1 transfer sweep"
  timeout 900 python -m cme213_tpu.bench.run_all --out "$OUT" \
      --only transfer_bandwidth 2>>"$OUT/tranche1.stderr.log" || true
fi

# bank whatever landed: commit the tranche files independently of the long
# sweeps.  The pathspec is built from files that actually exist — a short
# window that produced only the kernel rows (no transfer CSV yet) must
# still commit them, and `git add` of a missing path would fatal the
# whole chain.  Retries cover a concurrent index lock from the session.
if [ "$OUT" = "bench_results" ]; then
  bankfiles=""
  for f in "$OUT"/tranche1_*.json "$OUT"/transfer_bandwidth.csv; do
    [ -e "$f" ] && bankfiles="$bankfiles $f"
  done
  if [ -n "$bankfiles" ] \
     && [ -n "$(git status --porcelain -- $bankfiles 2>/dev/null)" ]; then
    for try in 1 2 3; do
      if git add -- $bankfiles 2>/dev/null \
         && git commit -m "Bank device tranche-1 rows (headline xla, pipeline-k4, transfer sweep)" \
              -- $bankfiles; then
        break
      fi
      sleep 5
    done
  fi
fi

# exit contract: conclusive on both rows unblocks the full capture (the
# f32 bench re-measures xla anyway); only device-tagged failures make
# the tranche incomplete and the window retry
row_conclusive "$OUT/tranche1_xla.json" \
  && row_conclusive "$OUT/tranche1_pipeline-k4.json"
