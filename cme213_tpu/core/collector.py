"""Live gang collector — tail N per-rank trace sinks into one fleet view.

The reference's hw5 prints per-rank MPI timing tables only after the run
finishes; our existing ``trace merge`` has the same post-mortem shape — it
parses complete files.  This module is the *live* half of fleet telemetry:
it tails every rank's JSON-lines sink concurrently (inotify-free polling,
so it works on any filesystem CI gives us), merges the records into one
causally-ordered stream keyed by the process-spanning trace id
(``core/trace.py``), and maintains the rolling aggregates the consoles
read — per-rank heartbeat freshness, epoch-commit lag, shed/breaker/
SLO-burn counters, span histograms.

Consumers:

- ``python -m cme213_tpu collect`` (this module's CLI): one-shot merged
  state (``--once``/``--json``) or a followed merged JSONL stream
  (``--follow``) — the gang-wide ``tail -f``.
- ``python -m cme213_tpu top`` (``top_cli.py``): the live console.
- ``trace merge --follow`` (``trace_cli.py``): same tailer, timeline or
  JSONL output.
- ``dist/launch.py``: :func:`write_fleet_exposition` folds every rank's
  final ``metrics-snapshot`` into the federated Prometheus file
  (``CME213_METRICS_FILE``) when the gang ends.

Tailing is rotation- and truncation-safe (an inode change or a shrinking
file resets the cursor) and partial-line tolerant (a torn tail line is
buffered until its newline arrives) — a rank hard-killed mid-write or a
logrotate race must never corrupt the merged view, only delay it.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import sys
import time

#: events that condemn/describe gang lifecycle vs. per-request flow
_SHED_EVENTS = {"queue-shed", "deadline-shed", "admission-rejected"}


class SinkTailer:
    """Incremental reader for one JSON-lines sink file.

    ``poll()`` returns the complete records appended since the last call.
    The file may not exist yet (a rank that hasn't opened its sink), may
    be rotated (inode change) or truncated (size below the cursor) — both
    reset the cursor to 0 so the replacement file is read from its start.
    A partial trailing line is buffered, not parsed; malformed complete
    lines are counted (``malformed``) and skipped.
    """

    def __init__(self, path: str):
        self.path = str(path)
        self.malformed = 0
        self._offset = 0
        self._sig: tuple | None = None
        self._buf = b""

    def poll(self) -> list[dict]:
        try:
            st = os.stat(self.path)
        except OSError:
            return []
        sig = (st.st_ino, st.st_dev)
        if sig != self._sig or st.st_size < self._offset:
            # rotated (new inode) or truncated: restart from the top
            self._offset, self._buf, self._sig = 0, b"", sig
        if st.st_size <= self._offset:
            return []
        try:
            # binary mode: offsets are byte-exact (text-mode tell() lies)
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                chunk = f.read()
                self._offset = f.tell()
        except OSError:
            return []
        lines = (self._buf + chunk).split(b"\n")
        self._buf = lines.pop()  # b"" when the chunk ended on a newline
        records = []
        for raw in lines:
            raw = raw.strip()
            if not raw:
                continue
            try:
                doc = json.loads(raw.decode("utf-8", errors="replace"))
            except ValueError:
                self.malformed += 1
                continue
            if not isinstance(doc, dict) or "event" not in doc:
                self.malformed += 1
                continue
            doc["_file"] = self.path
            records.append(doc)
        return records


def _rank_key(rec: dict) -> str:
    rank = rec.get("rank")
    return f"r{rank}" if rank is not None else "main"


def _rank_sort_key(label: str):
    if label.startswith("r") and label[1:].isdigit():
        return (0, int(label[1:]), label)
    return (1, 0, label)


def _new_row() -> dict:
    return {"pid": None, "incarnation": 0, "state": "unknown", "step": None,
            "heartbeat_t": None, "last_span": None, "last_event": None,
            "last_t": None, "breakers_open": 0, "degraded": False,
            "events": 0, "metrics": None, "role": None, "occupancy": None}


class Collector:
    """Merge N tailed sinks into rolling fleet aggregates.

    ``patterns`` may mix literal paths and globs; globs are re-expanded
    on every ``poll()`` so ranks that open their sink late (or replicas
    that join) are picked up without a restart.  Each ``poll()`` returns
    the new batch, time-ordered across files — the causally-ordered
    merged stream — and folds it into ``state()``.
    """

    def __init__(self, patterns):
        self.patterns = [str(p) for p in patterns]
        self._tailers: dict[str, SinkTailer] = {}
        self.trace_ids: set = set()
        self.ranks: dict[str, dict] = {}
        self.fleet: collections.Counter = collections.Counter()
        self.spans: dict[str, dict] = {}
        self.verdicts: list[dict] = []
        #: per-op convergence rows from solver-progress events (the
        #: STALLED verdict `top` renders; same policy as
        #: core/numerics.ConvergenceTracker's default)
        self.solvers: dict[str, dict] = {}
        #: durable long-job rows from job-* lifecycle events (serve/jobs.py)
        self.jobs: dict[str, dict] = {}
        self.recent: collections.deque = collections.deque(maxlen=64)
        #: slowest request hops seen (serve.hop.client / serve.hop.route
        #: span-ends), descending ms — the "which requests hurt" ribbon
        self.slowest: list[dict] = []
        self.last_commit: dict | None = None
        self.last_rc = None
        self.events = 0
        self.last_t: float | None = None

    # ------------------------------------------------------------ tailing

    def _expand(self) -> None:
        for pat in self.patterns:
            paths = (sorted(glob.glob(pat))
                     if any(ch in pat for ch in "*?[") else [pat])
            for p in paths:
                if p not in self._tailers:
                    self._tailers[p] = SinkTailer(p)

    def poll(self) -> list[dict]:
        self._expand()
        batch: list[dict] = []
        for tailer in self._tailers.values():
            batch.extend(tailer.poll())
        # time-order across files: each sink is append-ordered already,
        # so a stable sort on t interleaves ranks causally (same-clock
        # single host; cross-host skew is a known Dapper-style caveat)
        batch.sort(key=lambda r: (r.get("t") or 0.0))
        for rec in batch:
            self._ingest(rec)
        return batch

    # ---------------------------------------------------------- ingestion

    def _ingest(self, rec: dict) -> None:
        self.events += 1
        event = rec.get("event")
        t = rec.get("t")
        if isinstance(t, (int, float)):
            self.last_t = t if self.last_t is None else max(self.last_t, t)
        trace = rec.get("trace")
        if trace:
            self.trace_ids.add(str(trace))

        key = _rank_key(rec)
        row = self.ranks.setdefault(key, _new_row())
        row["events"] += 1
        row["last_event"] = event
        row["last_t"] = t
        if event not in ("rank-failed", "replica-down"):
            # rank-failed / replica-down are a supervisor reporting on a
            # condemned worker: the record mixes the emitter's identity
            # with the worker's (launcher pid + worker rank; front-tier
            # rank + replica incarnation) — folding it into either row's
            # pid/incarnation state would cross-contaminate them
            row["pid"] = rec.get("pid", row["pid"])
            inc = rec.get("incarnation", row["incarnation"]) or 0
            if inc != row["incarnation"]:
                # a restarted incarnation starts clean: stale failure
                # state must not shadow the replacement process
                row.update(incarnation=inc, state="unknown",
                           breakers_open=0, degraded=False)

        if event == "heartbeat":
            row["state"] = "running"
            row["step"] = rec.get("step")
            row["heartbeat_t"] = t
        elif event == "rank-failed":
            row["state"] = "failed"
            self.verdicts.append({"rank": rec.get("rank"),
                                  "reason": rec.get("reason"),
                                  "incarnation": rec.get("incarnation"),
                                  "t": t})
            self.fleet["verdicts"] += 1
        elif event == "gang-launch":
            self.fleet["launches"] += 1
        elif event == "gang-restart":
            self.fleet["restarts"] += 1
        elif event == "gang-exit":
            self.fleet["exits"] += 1
            self.last_rc = rec.get("rc")
        elif event == "epoch-commit":
            self.fleet["commits"] += 1
            self.last_commit = {"epoch": rec.get("epoch"),
                                "step": rec.get("step"), "t": t}
        elif event in _SHED_EVENTS:
            self.fleet["sheds"] += 1
        elif event == "slo-burn":
            self.fleet["slo_burns"] += 1
        elif event == "breaker-open":
            self.fleet["breaker_opens"] += 1
            row["breakers_open"] += 1
        elif event == "breaker-close":
            row["breakers_open"] = max(0, row["breakers_open"] - 1)
        elif event == "request-served":
            self.fleet["requests"] += 1
        elif event == "replica-up":
            # emitted by the replica worker itself: its row is `key`
            self.fleet["replica_ups"] += 1
            row.update(role="replica", state="running")
        elif event == "replica-down":
            # emitted by the fleet front tier ABOUT a replica — like
            # rank-failed, the condemned row is the replica's, not the
            # emitter's
            self.fleet["replica_downs"] += 1
            target = self.ranks.get(f"r{rec.get('replica')}")
            if target is not None:
                target["state"] = ("retired"
                                   if rec.get("reason") == "retired"
                                   else "down")
        elif event == "request-routed":
            self.fleet["routed"] += 1
        elif event == "request-requeued":
            self.fleet["requeues"] += 1
        elif event == "scale-up":
            self.fleet["scale_ups"] += 1
        elif event == "scale-down":
            self.fleet["scale_downs"] += 1
        elif event == "batch-executed":
            occ = rec.get("occupancy")
            if isinstance(occ, (int, float)):
                row["occupancy"] = occ
        elif event == "conformance-failed":
            self.fleet["conformance_failures"] += 1
        elif event == "attribution-mismatch":
            self.fleet["attribution_mismatches"] += 1
        elif event == "numeric-drift":
            self.fleet["drift_samples"] += 1
            if rec.get("over_budget"):
                self.fleet["drift_over_budget"] += 1
        elif event == "drift-budget-burn":
            self.fleet["drift_demotions"] += 1
        elif event == "numeric-sentinel":
            self.fleet["sentinel_trips"] += 1
        elif event == "solver-progress":
            self._ingest_progress(rec)
        elif event in ("job-submitted", "job-epoch", "job-preempted",
                       "job-resumed", "job-done", "job-reassigned"):
            self._ingest_job(event, rec)
        elif event == "served" and rec.get("demoted"):
            row["degraded"] = True
        elif event == "flight-dump":
            row["state"] = "crashed"
        elif event == "span-begin":
            row["last_span"] = rec.get("span")
        elif event == "span-end":
            name = rec.get("span")
            ms = rec.get("ms")
            if name and isinstance(ms, (int, float)):
                agg = self.spans.setdefault(
                    name, {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
                agg["count"] += 1
                agg["total_ms"] = round(agg["total_ms"] + ms, 3)
                agg["max_ms"] = max(agg["max_ms"], round(ms, 3))
                if name in ("serve.hop.client", "serve.hop.route"):
                    self._note_slowest(rec, ms)
        elif event == "metrics-snapshot":
            if isinstance(rec.get("metrics"), dict):
                row["metrics"] = rec["metrics"]

        if event not in ("span-begin", "span-end", "heartbeat",
                         "solver-progress"):
            self.recent.append({"t": t, "rank": key, "event": event})

    #: slowest-traces ribbon depth
    _SLOWEST_N = 8

    def _note_slowest(self, rec: dict, ms: float) -> None:
        """Track the top-N slowest request hops.  The entry carries
        everything `trace waterfall` needs to pull the full tree: the
        rid tag (its argument) and the trace id (the cross-file join
        key).  Client and route hops both feed the ribbon — whichever
        tier's sink the collector can see still surfaces the pain."""
        self.slowest.append({
            "span": rec.get("span"), "ms": round(ms, 3),
            "rid": rec.get("rid"), "trace": rec.get("trace"),
            "rank": _rank_key(rec), "status": rec.get("status"),
            "requeues": rec.get("requeues"), "t": rec.get("t")})
        self.slowest.sort(key=lambda e: -e["ms"])
        del self.slowest[self._SLOWEST_N:]

    #: solver-progress stall policy (matches ConvergenceTracker defaults)
    _STALL_EPOCHS = 5
    _MIN_IMPROVE = 1e-3

    def _ingest_progress(self, rec: dict) -> None:
        # keyed by (op, job): two concurrent jobs running the same op
        # must not fold into one convergence row
        op = str(rec.get("op") or "solver")
        if rec.get("job"):
            op = f"{op}[{rec['job']}]"
        res = rec.get("residual")
        if not isinstance(res, (int, float)):
            return
        row = self.solvers.setdefault(op, {
            "step": None, "residual": None, "iters_per_s": None,
            "best": None, "since_improve": 0, "stalled": False})
        row["step"] = rec.get("step")
        row["residual"] = res
        row["iters_per_s"] = rec.get("iters_per_s")
        best = row["best"]
        if best is None or res < best * (1.0 - self._MIN_IMPROVE):
            row["best"] = res
            row["since_improve"] = 0
        else:
            row["since_improve"] += 1
        row["stalled"] = row["since_improve"] >= self._STALL_EPOCHS

    def _ingest_job(self, event: str, rec: dict) -> None:
        jid = str(rec.get("job") or "?")
        row = self.jobs.setdefault(jid, {
            "op": rec.get("op"), "state": None, "epoch": None,
            "total_epochs": None, "residual": None, "epochs_seen": 0,
            "resumes": 0, "preemptions": 0, "reassigned": 0,
            "owner": None, "last_t": rec.get("t")})
        row["last_t"] = rec.get("t")
        if event == "job-submitted":
            row.update(op=rec.get("op"), state="PENDING",
                       total_epochs=rec.get("total_epochs"))
            self.fleet["jobs_submitted"] += 1
        elif event == "job-epoch":
            row.update(state="RUNNING", epoch=rec.get("epoch"),
                       residual=rec.get("residual"))
            row["epochs_seen"] += 1
            self.fleet["job_epochs"] += 1
        elif event == "job-preempted":
            row.update(state="PREEMPTED", epoch=rec.get("epoch"))
            row["preemptions"] += 1
            self.fleet["job_preemptions"] += 1
        elif event == "job-resumed":
            row.update(state="RUNNING", epoch=rec.get("epoch"))
            row["resumes"] += 1
            self.fleet["job_resumes"] += 1
        elif event == "job-reassigned":
            row["reassigned"] += 1
            row["owner"] = rec.get("target")
            self.fleet["job_reassignments"] += 1
        else:                            # job-done
            row.update(state=rec.get("state"))
            self.fleet["jobs_done"] += 1

    # ------------------------------------------------------------- output

    def state(self) -> dict:
        """The merged fleet view, deterministic for ``--once --json``:
        ages are computed against the newest *observed* event time, not
        the wall clock, so re-rendering an idle capture is stable."""
        now_t = self.last_t
        ranks_out = {}
        for key in sorted(self.ranks, key=_rank_sort_key):
            row = dict(self.ranks[key])
            hb = row.get("heartbeat_t")
            row["heartbeat_age_s"] = (
                round(now_t - hb, 3)
                if hb is not None and now_t is not None else None)
            ranks_out[key] = row
        commit_lag_s = (
            round(now_t - self.last_commit["t"], 3)
            if self.last_commit and self.last_commit.get("t") is not None
            and now_t is not None else None)
        return {
            "files": sorted(self._tailers),
            "events": self.events,
            "malformed": sum(t.malformed for t in self._tailers.values()),
            "trace_ids": sorted(self.trace_ids),
            "ranks": ranks_out,
            "fleet": dict(sorted(self.fleet.items())),
            "verdicts": list(self.verdicts),
            "solvers": {k: dict(v) for k, v in sorted(self.solvers.items())},
            "jobs": {k: dict(v) for k, v in sorted(self.jobs.items())},
            "spans": {k: dict(v) for k, v in sorted(self.spans.items())},
            "slowest_traces": [dict(e) for e in self.slowest],
            "recent": list(self.recent),
            "last_rc": self.last_rc,
            "last_commit": self.last_commit,
            "commit_lag_s": commit_lag_s,
        }

    def fleet_snapshots(self) -> dict[str, dict]:
        """Last seen per-rank metrics snapshot, keyed by rank label —
        the input shape ``metrics.render_prometheus(fleet=...)`` takes."""
        return {key: row["metrics"] for key, row in self.ranks.items()
                if isinstance(row.get("metrics"), dict)}


def write_fleet_exposition(sink_paths, path: str | None = None,
                           extra: dict[str, dict] | None = None) -> str | None:
    """Fold the final ``metrics-snapshot`` of every sink in ``sink_paths``
    (plus ``extra`` — e.g. the launcher's own live registry) into one
    federated Prometheus exposition at ``path`` (default
    ``CME213_METRICS_FILE``).  Atomic tmp + ``os.replace``; the written
    path is pinned against the atexit single-process overwrite.  Returns
    the path written, or None when unconfigured or nothing to expose."""
    from . import metrics

    path = path or os.environ.get(metrics.METRICS_FILE_ENV)
    if not path:
        return None
    coll = Collector(sink_paths)
    coll.poll()
    fleet = coll.fleet_snapshots()
    if extra:
        fleet.update(extra)
    text = metrics.render_prometheus(fleet=fleet) if fleet else ""
    if not text:
        return None
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    metrics.suppress_exit_exposition(path)
    return path


def render_state(state: dict, out) -> None:
    """Compact text rendering of :meth:`Collector.state` (the ``collect``
    one-shot default; ``top`` owns the full console)."""
    ids = state["trace_ids"]
    out.write(f"fleet: {len(state['ranks'])} proc(s), "
              f"{state['events']} event(s), "
              f"{len(ids)} trace id(s)"
              + (f" [{ids[0]}]" if len(ids) == 1 else "") + "\n")
    for key, row in state["ranks"].items():
        hb = row["heartbeat_age_s"]
        out.write(f"  {key:<6} {row['state']:<8} pid={row['pid']} "
                  f"inc={row['incarnation']} step={row['step']} "
                  f"hb_age={hb if hb is not None else '-'}s "
                  f"last={row['last_event']}\n")
    if state["fleet"]:
        out.write("  fleet counters: "
                  + " ".join(f"{k}={v}"
                             for k, v in state["fleet"].items()) + "\n")
    if state["verdicts"]:
        for v in state["verdicts"]:
            out.write(f"  verdict: rank {v['rank']} {v['reason']} "
                      f"(incarnation {v['incarnation']})\n")
    for e in state.get("slowest_traces", [])[:4]:
        out.write(f"  slow: {e['ms']}ms {e['span']} rid={e['rid']} "
                  f"trace={e['trace']} ({e['rank']}"
                  + (f", {e['requeues']} requeue(s)" if e.get("requeues")
                     else "") + ")\n")
    if state["malformed"]:
        out.write(f"  malformed lines skipped: {state['malformed']}\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="cme213_tpu collect",
        description="tail per-rank trace sinks into one live fleet view")
    ap.add_argument("files", nargs="+",
                    help="sink files or globs (re-expanded every poll)")
    ap.add_argument("--once", action="store_true",
                    help="read what exists now, print the merged state, "
                         "exit (the default unless --follow)")
    ap.add_argument("--json", action="store_true",
                    help="print the merged state as deterministic JSON")
    ap.add_argument("--follow", action="store_true",
                    help="stream the merged record stream as JSONL until "
                         "interrupted (or --max-seconds)")
    ap.add_argument("--interval", type=float, default=0.5,
                    help="seconds between polls in --follow mode")
    ap.add_argument("--max-seconds", type=float, default=None,
                    help="stop following after this many seconds")
    args = ap.parse_args(argv)

    coll = Collector(args.files)
    if args.follow and not args.once:
        deadline = (time.monotonic() + args.max_seconds
                    if args.max_seconds else None)
        try:
            while True:
                for rec in coll.poll():
                    out = {k: v for k, v in rec.items() if k != "_file"}
                    print(json.dumps(out, sort_keys=True, default=str),
                          flush=True)
                if deadline is not None and time.monotonic() > deadline:
                    break
                time.sleep(args.interval)
        except KeyboardInterrupt:
            pass
        return 0
    coll.poll()
    state = coll.state()
    if args.json:
        print(json.dumps(state, sort_keys=True, default=str))
    else:
        render_state(state, sys.stdout)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
