"""The program cache (core/programs.py) and its two companions: pad-and-
mask shape canonicalization (spmv requests snapped to power-of-two
buckets, outputs sliced back bitwise-equal) and persistent-cache warm
starts (a second process compiles nothing fresh for known shapes).

The contract under test is the CUDA reference's load-module-once
discipline: one compile per (op, rung, shape class, dtype, statics) per
process, a dict lookup ever after — measured, not assumed, via the
retrace detector and the program-cache hit/miss telemetry.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from cme213_tpu.core import metrics, programs, trace

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.flush_sink()
    trace.clear_events()   # also resets the program cache
    metrics.reset()
    yield
    trace.flush_sink()
    trace.clear_events()
    metrics.reset()


# ------------------------------------------------------------ cache unit

def test_canonical_size_buckets():
    assert programs.canonical_size(1) == 1
    assert programs.canonical_size(2) == 2
    assert programs.canonical_size(3) == 4
    assert programs.canonical_size(512) == 512
    assert programs.canonical_size(513) == 1024
    assert programs.canonical_size(1000) == 1024
    assert programs.canonical_size(3, floor=16) == 16


def test_miss_builds_and_warms_once_then_hits():
    calls = {"build": 0, "warm": 0}

    def build():
        calls["build"] += 1
        return lambda x: x + 1

    def warm(fn):
        calls["warm"] += 1
        assert fn(1) == 2

    fn1 = programs.get("probe", "r", "n8", build, dtype="f32", warm=warm,
                       iters=2)
    assert calls == {"build": 1, "warm": 1}
    fn2 = programs.get("probe", "r", "n8", build, dtype="f32", warm=warm,
                       iters=2)
    assert fn2 is fn1 and calls == {"build": 1, "warm": 1}
    assert programs.size() == 1
    # telemetry: one miss (with its compile span feeding the compile
    # histogram), one hit, and the counters that loadgen's attribution
    # section diffs
    assert len(trace.events("program-cache-miss")) == 1
    assert len(trace.events("program-cache-hit")) == 1
    hit = trace.events("program-cache-hit")[0]
    assert (hit["op"], hit["rung"], hit["shape_class"]) == ("probe", "r", "n8")
    snap = metrics.snapshot()
    assert snap["counters"]["programs.hits"] == 1
    assert snap["counters"]["programs.misses"] == 1
    assert snap["histograms"]["compile.probe.n8.ms"]["count"] == 1


def test_key_includes_statics_and_dtype():
    built = []

    def build_tagged(tag):
        def build():
            built.append(tag)
            return tag
        return build

    programs.get("op", "r", "n8", build_tagged("a"), dtype="f32", iters=2)
    programs.get("op", "r", "n8", build_tagged("b"), dtype="f32", iters=3)
    programs.get("op", "r", "n8", build_tagged("c"), dtype="f64", iters=2)
    programs.get("op", "r", "n8", build_tagged("d"), dtype="f32", iters=2,
                 tile=64)
    assert built == ["a", "b", "c", "d"]   # every variant is its own program
    assert programs.size() == 4
    # and the original key still hits
    assert programs.get("op", "r", "n8", build_tagged("e"), dtype="f32",
                        iters=2) == "a"


def test_failed_build_or_warm_caches_nothing():
    with pytest.raises(RuntimeError):
        programs.get("op", "r", "n8", lambda: (_ for _ in ()).throw(
            RuntimeError("no lowering")))
    assert programs.size() == 0
    with pytest.raises(RuntimeError):
        programs.get("op", "r", "n8", lambda: "fn",
                     warm=lambda fn: (_ for _ in ()).throw(
                         RuntimeError("warmup died")))
    assert programs.size() == 0
    # the key is not poisoned: a later good build caches normally
    assert programs.get("op", "r", "n8", lambda: "fn") == "fn"
    assert programs.size() == 1


def test_clear_events_resets_the_cache():
    programs.get("op", "r", "n8", lambda: "fn")
    assert programs.size() == 1
    trace.clear_events()   # fresh telemetry slate implies a cold cache
    assert programs.size() == 0 and programs.keys() == []


# ----------------------------------------- zero-retrace second dispatch

def test_spmv_second_call_is_all_hits():
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(256, 6, 32, iters=3, seed=11)
    out1 = sp.run_spmv_scan(prob, kernel="flat")
    n_miss = len(trace.events("program-cache-miss"))
    n_hit = len(trace.events("program-cache-hit"))
    out2 = sp.run_spmv_scan(prob, kernel="flat")
    assert len(trace.events("program-cache-miss")) == n_miss
    assert len(trace.events("program-cache-hit")) > n_hit
    assert trace.events("compile-retrace") == []
    np.testing.assert_array_equal(out1, out2)


def test_heat_second_call_is_all_hits():
    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops.stencil_pipeline import run_heat_resilient

    p = SimParams(nx=24, ny=24, order=2, iters=3)
    u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
    r1 = run_heat_resilient(jnp.array(u0), 3, 2, p.xcfl, p.ycfl, p.bc,
                            k=1, interpret=True)
    n_miss = len(trace.events("program-cache-miss"))
    r2 = run_heat_resilient(jnp.array(u0), 3, 2, p.xcfl, p.ycfl, p.bc,
                            k=1, interpret=True)
    assert len(trace.events("program-cache-miss")) == n_miss
    assert trace.events("compile-retrace") == []
    np.testing.assert_array_equal(np.asarray(r1.value), np.asarray(r2.value))


def test_serve_cipher_second_batch_is_a_hit():
    from cme213_tpu.serve.workloads import CipherAdapter, CipherRequest

    adapter = CipherAdapter()
    reqs = [CipherRequest(np.arange(64, dtype=np.uint8), s) for s in (3, 7)]
    out1 = adapter.run_batch(reqs, "bytes")
    n_miss = len(trace.events("program-cache-miss"))
    out2 = adapter.run_batch(reqs, "bytes")
    assert len(trace.events("program-cache-miss")) == n_miss
    assert trace.events("program-cache-hit")
    assert trace.events("compile-retrace") == []
    for a, b in zip(out1, out2):
        np.testing.assert_array_equal(a, b)


# ------------------------------------------------ pad-and-mask equality

def test_canonical_solve_bitwise_equals_unpadded():
    from cme213_tpu.apps import spmv_scan as sp

    # sizes straddling the mask edges: one-below-bucket (pad by 1),
    # just-over-half (maximal pad), and exactly-on-bucket (no pad at all)
    for n in (1023, 513, 512):
        prob = sp.generate_problem(n, 8, 32, iters=3, seed=n)
        base = sp.run_spmv_scan(prob, kernel="flat")
        canon = sp.run_spmv_scan(prob, kernel="flat", canonical=True)
        assert canon.shape == (n,)
        np.testing.assert_array_equal(canon, base)
        # the solve ran in the canonical class (or its own, when already
        # canonical) — and the bucket was conformance-probed first
        n_to = programs.canonical_size(n)
        assert any(k[2] == f"n{n_to}/i3" for k in programs.keys())


def test_bucket_gate_refuses_unpaddable_bucket():
    from cme213_tpu.apps.spmv_scan import _bucket_gate

    # a bucket too small to hold a strictly-smaller probe can't be proven
    assert _bucket_gate(2, "flat", jnp.float32) is False


def test_serve_mixed_sizes_pad_into_one_bucket_bitwise():
    from cme213_tpu.apps import spmv_scan as sp
    from cme213_tpu.serve.workloads import SpmvAdapter

    adapter = SpmvAdapter()
    probs = [sp.generate_problem(500, 8, 32, iters=3, seed=1),
             sp.generate_problem(512, 8, 32, iters=3, seed=2)]
    # near-sized requests share one canonical class -> one batched program
    assert {adapter.shape_class(p) for p in probs} == {"n512/i3"}
    outs = adapter.run_batch(probs, "flat")
    for p, out in zip(probs, outs):
        assert out.shape == (p.n,)
        ref = sp.run_spmv_scan(p, kernel="flat")
        np.testing.assert_array_equal(np.asarray(out), ref)


# --------------------------------------------------- loadgen retrace gate

def test_loadgen_max_retraces_gate(capsys):
    from cme213_tpu.serve import loadgen

    argv = ["--requests", "4", "--mode", "closed", "--concurrency", "2",
            "--max-batch", "2", "--mix", "cipher", "--seed", "0"]
    # the program cache holds steady-state retraces at zero even on a
    # cold pass: every shape class compiles at most once
    assert loadgen.main([*argv, "--max-retraces", "0"]) == 0
    out = capsys.readouterr().out
    assert "program cache" in out
    # the gate trips: any retrace count exceeds a -1 ceiling
    assert loadgen.main([*argv, "--max-retraces", "-1"]) == 1
    assert "--max-retraces=-1" in capsys.readouterr().err


# --------------------------------------------------- trace summary column

def test_trace_summary_shows_hit_miss_column(tmp_path, monkeypatch, capsys):
    from cme213_tpu import trace_cli

    path = tmp_path / "t.jsonl"
    monkeypatch.setenv(trace.TRACE_FILE_ENV, str(path))
    programs.get("probe", "r", "n8", lambda: "fn")
    programs.get("probe", "r", "n8", lambda: "fn")
    trace.flush_sink()
    monkeypatch.delenv(trace.TRACE_FILE_ENV)
    capsys.readouterr()
    assert trace_cli.main(["summary", str(path)]) == 0
    out = capsys.readouterr().out
    assert "hit/miss" in out
    assert "probe [n8]" in out
    assert "1/1" in out


# ------------------------------------------------ persistent warm starts

def test_second_process_compiles_nothing_fresh(tmp_path):
    """The warm-start acceptance, subprocess-verified: process 1 warms
    the cipher buckets into a persistent XLA disk cache; process 2 runs
    the same warmup and adds ZERO entries — every program loads from
    disk instead of compiling fresh."""
    cache = tmp_path / "xla-cache"
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "CME213_COMPILE_CACHE": str(cache)}
    cmd = [sys.executable, "-m", "cme213_tpu", "serve", "warmup",
           "--mix", "cipher", "--requests", "2", "--max-batch", "2",
           "--json"]

    def run():
        r = subprocess.run(cmd, env=env, cwd=REPO_ROOT, timeout=300,
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        return json.loads(r.stdout)

    rep1 = run()
    assert rep1["warmed"] and rep1["programs"] > 0
    entries = rep1["persistent_entries"]
    if not entries:
        pytest.skip("backend wrote no persistent compilation cache entries")
    assert rep1["compile"]["cache_misses"] > 0
    rep2 = run()
    # zero fresh entries persisted: the disk cache served every compile
    assert rep2["persistent_entries"] == entries
    assert rep2["warmed"] == rep1["warmed"]
