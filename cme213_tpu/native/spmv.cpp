// Host-native OpenMP SpMV-scan — the hw_final CPU axis.
//
// The reference's final project carries a CPU reference path measured
// alongside the GPU kernel (4-thread suite table in data.ods): per
// iteration, an OpenMP parallel elementwise multiply followed by a
// one-segment-per-thread serial inclusive scan
// (cf. hw/hw_final/programming/fp.cu:130-152).  This is that component,
// rebuilt for the framework's C ABI: float accumulation (matching the
// device pipeline's checked precision), explicit ping-pong buffers, and
// the segment list passed WITHOUT the terminal sentinel (segment i spans
// [s[i], s[i+1]) with an implicit end at n).

#include <cstdint>
#include <cstring>
#include <vector>

#include <omp.h>

extern "C" {

// a <- segscan(a * xx) iterated `iters` times; result lands back in `a`.
// s holds `p` segment starts (no sentinel), strictly increasing, s[0]==0.
void spmv_scan_omp(float* a, const float* xx, const int32_t* s, long p,
                   long n, int iters) {
  std::vector<float> tmp(n);
  float* src = a;
  float* dst = tmp.data();
  for (int it = 0; it < iters; ++it) {
#pragma omp parallel
    {
#pragma omp for schedule(static)
      for (long l = 0; l < n; ++l) dst[l] = src[l] * xx[l];
      // one segment per thread, serial scan inside — segment lengths are
      // skewed in the SuiteSparse instances, so dynamic scheduling keeps
      // threads busy (the reference's plain `omp for` equivalent)
#pragma omp for schedule(dynamic, 16)
      for (long i = 0; i < p; ++i) {
        long lo = s[i];
        long hi = (i + 1 < p) ? s[i + 1] : n;
        float acc = 0.0f;
        for (long j = lo; j < hi; ++j) {
          acc += dst[j];
          dst[j] = acc;
        }
      }
    }
    std::swap(src, dst);
  }
  if (src != a) std::memcpy(a, src, n * sizeof(float));
}

}  // extern "C"
