"""Functional halo-grid abstraction.

Re-designs the reference's ``Grid<floatType>`` (flattened halo storage with an
imperative ping-pong ``gridState`` selector, ``hw/hw2/programming/2dHeat.cu:
230-348``) the JAX way: the grid is an immutable ``(gy, gx)`` array; the
"ping-pong" is functional state threading (old array in, new array out) with
XLA buffer donation doing the double-buffer reuse (strategy P13 in SURVEY
§2.7).  Layout matches the reference: x contiguous, y rows; element (x, y) is
``grid[y, x]``; y=0 is the *bottom* row (reference prints top row first by
iterating y downward, ``2dHeat.cu:283-293``).

Dirichlet BCs occupy the full border band of width ``border_size`` (bottom and
top bands first over all x, then left/right bands over all y overwriting the
corners — same order as the reference's BC loops, ``2dHeat.cu:326-344``).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..config import SimParams


@dataclass(frozen=True)
class HaloGrid:
    """Static grid geometry (the non-array part of the reference's Grid)."""

    nx: int
    ny: int
    border_size: int

    @property
    def gx(self) -> int:
        return self.nx + 2 * self.border_size

    @property
    def gy(self) -> int:
        return self.ny + 2 * self.border_size

    @classmethod
    def from_params(cls, params: SimParams) -> "HaloGrid":
        # same validity asserts as reference Grid ctor (2dHeat.cu:312-313)
        assert params.nx > 2 * params.border_size
        assert params.ny > 2 * params.border_size
        return cls(nx=params.nx, ny=params.ny, border_size=params.border_size)


def make_initial_grid(params: SimParams, dtype=jnp.float32) -> jnp.ndarray:
    """(gy, gx) array: interior = ic, border bands = Dirichlet BC values.

    BC band order matches the reference (bottom/top bands, then left/right
    bands overwrite the corners — ``hw/hw2/programming/2dHeat.cu:326-344``).
    """
    b = params.border_size
    g = np.full((params.gy, params.gx), params.ic, dtype=np.float64)
    g[:b, :] = params.bc_bottom
    g[b + params.ny:, :] = params.bc_top
    g[:, :b] = params.bc_left
    g[:, b + params.nx:] = params.bc_right
    return jnp.asarray(g, dtype=dtype)


def interior(grid: jnp.ndarray, border_size: int) -> jnp.ndarray:
    """The (ny, nx) interior view of a halo grid."""
    b = border_size
    return grid[b:-b, b:-b] if b else grid


def save_grid_to_file(grid, path: str) -> None:
    """Text dump, top row first — the format of ``Grid::saveStateToFile``
    (``hw/hw2/programming/2dHeat.cu:283-293,350-359``): 3 significant digits,
    width-5 fields, y descending."""
    g = np.asarray(grid)
    with open(path, "w") as f:
        for y in range(g.shape[0] - 1, -1, -1):
            f.write(" ".join(f"{v:5.3g}" for v in g[y]) + " \n")
        f.write("\n")
