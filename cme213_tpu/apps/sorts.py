"""Sorts workload driver (reference hw4).

Driver orchestration of ``hw/hw4/programming/mergesort.cpp:146-195`` and
``radixsort.cpp:163-215``: generate random keys, run the ``std::sort``-class
golden, run the parallel implementations, assert element-wise equality, and
report times/throughputs.  Implementations available:

- host-native OpenMP merge sort / LSD radix sort (``cme213_tpu.native``) —
  the parity components for the reference's CPU-scaling claims;
- TPU-resident radix and bitonic sorts (``ops/sort.py``).

CLI mirrors the reference knobs: ``sort_threshold merge_threshold
num_elements run_serial``.
"""

from __future__ import annotations

import sys
import time

import numpy as np

from ..core.trace import span
from ..verify import check_exact

# the timed sections below mirror the reference's omp_get_wtime pairs
# (mergesort.cpp:168-184, radixsort.cpp:163-215): the perf_counter reads
# keep the contractual printouts, the enclosing spans put the same phases
# in `python -m cme213_tpu trace summary`


def run_merge_sort(num_elements: int = 1_000_000, sort_threshold: int = 4096,
                   merge_threshold: int = 4096, seed: int = 0) -> bool:
    from .. import native

    rng = np.random.default_rng(seed)
    keys = rng.integers(-(2**31), 2**31, size=num_elements,
                        dtype=np.int64).astype(np.int32)
    with span("sorts.std_sort", n=num_elements):
        t0 = time.perf_counter()
        golden = np.sort(keys)
        t_std = time.perf_counter() - t0

    data = keys.copy()
    with span("sorts.merge_sort", n=num_elements,
              threads=native.thread_count()):
        t0 = time.perf_counter()
        native.merge_sort(data, sort_threshold, merge_threshold)
        t_par = time.perf_counter() - t0
    print(f"std sort: {t_std:.3f} s, parallel merge sort: {t_par:.3f} s "
          f"({native.thread_count()} threads)")
    res = check_exact(golden, data, "merge sort")
    if not res:
        print(res.message)
    return bool(res)


def run_radix_sort(num_elements: int = 1_000_000, num_bits: int = 8,
                   block_size: int = 8192, run_serial: bool = True,
                   seed: int = 0, tpu: bool = False) -> bool:
    from .. import native

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 2**32, size=num_elements,
                        dtype=np.uint64).astype(np.uint32)
    golden = np.sort(keys)
    ok = True

    data = keys.copy()
    with span("sorts.radix_parallel", n=num_elements,
              threads=native.thread_count()):
        t0 = time.perf_counter()
        native.radix_sort(data, num_bits, block_size)
        t_par = time.perf_counter() - t0
    print(f"parallel radix: {num_elements / t_par / 1e6:.1f}e6 elems/s "
          f"({t_par:.3f} s, {native.thread_count()} threads)")
    res = check_exact(golden, data, "parallel radix")
    ok &= bool(res)

    if run_serial:
        data = keys.copy()
        with span("sorts.radix_serial", n=num_elements):
            t0 = time.perf_counter()
            native.radix_sort_serial(data, num_bits)
            t_ser = time.perf_counter() - t0
        print(f"serial radix: {num_elements / t_ser / 1e6:.1f}e6 elems/s")
        ok &= bool(check_exact(golden, data, "serial radix"))

    if tpu:
        import jax.numpy as jnp

        from ..ops import radix_sort as tpu_radix

        out = tpu_radix(jnp.asarray(keys), num_bits=num_bits,
                        block_size=block_size)
        ok &= bool(check_exact(golden, np.asarray(out), "tpu radix"))
    return ok


def main(argv: list[str]) -> int:
    sort_threshold = int(argv[1]) if len(argv) > 1 else 4096
    merge_threshold = int(argv[2]) if len(argv) > 2 else 4096
    num_elements = int(argv[3]) if len(argv) > 3 else 1_000_000
    run_serial = bool(int(argv[4])) if len(argv) > 4 else True
    ok = run_merge_sort(num_elements, sort_threshold, merge_threshold)
    ok &= run_radix_sort(num_elements, run_serial=run_serial)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))
