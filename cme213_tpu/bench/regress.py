"""Bench regression gate — fresh sweep artifacts vs banked baselines.

``python -m cme213_tpu.bench.regress [--fresh DIR] [--baseline DIR]
[--threshold F] [--strict] [--json PATH] [--bench JSON] [--history DIR]``
(also reachable as ``python -m cme213_tpu trace regress ...``).

The capture history shows why this exists: BENCH_r02's 14.62 GB/s was
0.61× the committed baseline and nothing flagged it — the regression was
found by a human reading JSON tails.  This gate makes that comparison
tooling:

- **Sweep CSVs** — for every CSV basename present in both directories,
  rows are matched on their identity columns (everything that is not a
  known metric column) and each shared metric column is compared.
  Higher-is-better metrics (``gbs``, ``gflops``, ``*_gbs``,
  ``radix_elems_per_s``, ``pct_peak``) regress when the fresh value
  drops below ``(1 - threshold) ×`` baseline; lower-is-better ones
  (``ms``, ``seconds``, ``merge_s``, ``cpu_ms``) when it rises above
  ``(1 + threshold) ×``.  A baseline row that measured fine but has no
  signal in the fresh run (error row / zeroed metric) is a regression
  too — a kernel that stopped producing data is the worst kind of slow.
- **metrics.json** — per-sweep row counts from ``bench/run_all.py``'s
  sidecar: a sweep that produced fewer rows than its baseline lost
  coverage.  The sidecar's ``compile.<op>.<class>.ms`` histograms are
  compared too (mean, lower-better); a compile histogram that vanished
  from the fresh run means the program cache served it warm and is
  never flagged.
- **Headline trajectory** — ``--bench`` (a ``bench.py`` JSON output or a
  capture file whose ``tail`` embeds one) compared against the best
  prior value across ``--history``'s ``BENCH_r*.json`` captures — the
  0.61×-vs-baseline class.

Output: human-readable lines plus a machine-readable verdict document
(``--json PATH``, also embedded in the exit semantics): exit 0 when
clean or advisory, 1 under ``--strict`` when any regression was found,
2 on unusable inputs.  Unmatched files/rows are reported but never fail
the gate — quick CI sweeps at toy sizes share no keys with full-size
banked baselines and must stay advisory-clean.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys

#: metric columns where bigger is better
HIGHER_BETTER = {"gbs", "gflops", "h2d_gbs", "d2h_gbs", "char_gbs",
                 "uint_gbs", "uint2_gbs", "achieved_gbs",
                 "radix_elems_per_s", "pct_peak", "mbs", "req_s"}
#: metric columns where smaller is better
LOWER_BETTER = {"ms", "seconds", "merge_s", "cpu_ms"}
#: columns that are neither identity nor comparable signal.  ``bytes``
#: is deliberately NOT here: it is derived from the problem shape, so it
#: serves as identity — keeping a quick toy-size row from matching a
#: full-size banked row that happens to share the visible key columns.
IGNORED = {"error", "rel_l2", "rel_l2_vs_flat", "bound", "evidence", "ok"}

#: default noise threshold (fraction): CPU sweep timings jitter by a few
#: percent run-to-run; 10% is far above noise and still catches the 20%+
#: drops that matter (a 0.61× event is a 39% drop)
DEFAULT_THRESHOLD = 0.1


def _fnum(v) -> float | None:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f


def _read_rows(path: str) -> list[dict]:
    with open(path, newline="") as f:
        return list(csv.DictReader(f))


def _row_key(row: dict) -> tuple:
    return tuple(sorted((k, str(v)) for k, v in row.items()
                        if k not in HIGHER_BETTER and k not in LOWER_BETTER
                        and k not in IGNORED))


def compare_rows(fname: str, fresh: list[dict],
                 base: list[dict], threshold: float) -> dict:
    """Per-file comparison: matched row pairs, regressions, improvements."""
    fresh_by_key = {_row_key(r): r for r in fresh}
    regs, imps, compared, unmatched = [], [], 0, 0
    for brow in base:
        frow = fresh_by_key.get(_row_key(brow))
        if frow is None:
            unmatched += 1
            continue
        key_txt = " ".join(f"{k}={v}" for k, v in _row_key(brow))
        for col in sorted((set(brow) & set(frow))
                          & (HIGHER_BETTER | LOWER_BETTER)):
            bval, fval = _fnum(brow.get(col)), _fnum(frow.get(col))
            if bval is None or bval <= 0:
                continue  # baseline had no signal for this metric
            compared += 1
            entry = {"file": fname, "row": key_txt, "metric": col,
                     "baseline": bval, "fresh": fval}
            if fval is None or fval <= 0:
                # measured before, error/zero now: always a regression
                regs.append({**entry, "ratio": 0.0})
                continue
            ratio = fval / bval
            entry["ratio"] = round(ratio, 4)
            if col in HIGHER_BETTER:
                if ratio < 1 - threshold:
                    regs.append(entry)
                elif ratio > 1 + threshold:
                    imps.append(entry)
            else:
                if ratio > 1 + threshold:
                    regs.append(entry)
                elif ratio < 1 - threshold:
                    imps.append(entry)
    return {"compared": compared, "unmatched_rows": unmatched,
            "regressions": regs, "improvements": imps}


def compare_dirs(fresh_dir: str, baseline_dir: str,
                 threshold: float) -> dict:
    """Compare every shared CSV (plus the metrics.json row counts)."""
    fresh_csvs = {f for f in os.listdir(fresh_dir) if f.endswith(".csv")}
    base_csvs = {f for f in os.listdir(baseline_dir) if f.endswith(".csv")}
    files, regs, imps = {}, [], []
    for fname in sorted(fresh_csvs & base_csvs):
        res = compare_rows(fname,
                           _read_rows(os.path.join(fresh_dir, fname)),
                           _read_rows(os.path.join(baseline_dir, fname)),
                           threshold)
        files[fname] = {"compared": res["compared"],
                        "unmatched_rows": res["unmatched_rows"],
                        "regressions": len(res["regressions"]),
                        "improvements": len(res["improvements"])}
        regs.extend(res["regressions"])
        imps.extend(res["improvements"])

    # metrics.json sidecar: lost sweep coverage is a regression
    for side in ("metrics.json",):
        fp, bp = (os.path.join(fresh_dir, side),
                  os.path.join(baseline_dir, side))
        if not (os.path.exists(fp) and os.path.exists(bp)):
            continue
        try:
            with open(fp) as f:
                fm = json.load(f)
            with open(bp) as f:
                bm = json.load(f)
        except ValueError:
            continue
        for sweep, brec in bm.items():
            brows = brec.get("rows")
            frows = fm.get(sweep, {}).get("rows")
            if not isinstance(brows, (int, float)) or brows <= 0:
                continue
            if not isinstance(frows, (int, float)) or frows < brows:
                regs.append({"file": side, "row": sweep, "metric": "rows",
                             "baseline": brows, "fresh": frows,
                             "ratio": round((frows or 0) / brows, 4)})
            # compile-time histograms (``compile.<op>.<class>.ms`` from
            # the program cache's miss spans): mean is lower-better.  A
            # compile histogram present in the baseline but absent from
            # the fresh run means the program came from a warm cache —
            # that's the win this sidecar exists to verify, never a
            # regression — so only pairs present on both sides compare.
            bhists = brec.get("metrics", {}).get("histograms", {})
            fhists = fm.get(sweep, {}).get("metrics", {}) \
                       .get("histograms", {})
            for hname in sorted(set(bhists) & set(fhists)):
                if not (hname.startswith("compile.")
                        and hname.endswith(".ms")):
                    continue
                bmean = _fnum(bhists[hname].get("mean"))
                fmean = _fnum(fhists[hname].get("mean"))
                if bmean is None or bmean <= 0 or fmean is None:
                    continue
                ratio = fmean / bmean
                entry = {"file": side, "row": sweep, "metric": hname,
                         "baseline": bmean, "fresh": fmean,
                         "ratio": round(ratio, 4)}
                if ratio > 1 + threshold:
                    regs.append(entry)
                elif ratio < 1 - threshold:
                    imps.append(entry)
    return {"files": files,
            "baseline_only": sorted(base_csvs - fresh_csvs),
            "fresh_only": sorted(fresh_csvs - base_csvs),
            "regressions": regs, "improvements": imps}


def _parse_bench_doc(path: str) -> dict | None:
    """A ``bench.py`` JSON output — either the document itself or a
    capture record whose ``tail`` embeds the JSON line."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(doc, dict) and "value" in doc:
        return doc
    for line in reversed(str(doc.get("tail", "")).splitlines()
                         if isinstance(doc, dict) else []):
        line = line.strip()
        if line.startswith("{"):
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and "value" in cand:
                return cand
    return None


def trajectory_check(bench_path: str, history_dir: str,
                     threshold: float) -> dict:
    """Fresh headline value vs the best prior BENCH_r* capture."""
    history = []
    try:
        names = sorted(os.listdir(history_dir))
    except OSError:
        names = []
    for name in names:
        if not (name.startswith("BENCH_r") and name.endswith(".json")):
            continue
        doc = _parse_bench_doc(os.path.join(history_dir, name))
        if doc and _fnum(doc.get("value")):
            history.append({"capture": name,
                            "value": float(doc["value"])})
    fresh = _parse_bench_doc(bench_path) if bench_path else None
    out = {"history": history, "fresh": None, "best_prior": None,
           "ratio": None, "regression": False}
    if not history or fresh is None or not _fnum(fresh.get("value")):
        return out
    best = max(history, key=lambda h: h["value"])
    value = float(fresh["value"])
    out.update(fresh=value, best_prior=best,
               ratio=round(value / best["value"], 4),
               regression=value < (1 - threshold) * best["value"])
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cme213_tpu.bench.regress",
        description="compare fresh bench artifacts against banked "
                    "baselines; exit nonzero under --strict on any "
                    "regression beyond the noise threshold")
    ap.add_argument("--fresh", default="bench_results",
                    help="directory with the fresh sweep CSVs + "
                         "metrics.json (default: bench_results)")
    ap.add_argument("--baseline", default=os.path.join("bench_results",
                                                       "cpu"),
                    help="banked baseline directory "
                         "(default: bench_results/cpu)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="noise threshold as a fraction "
                         f"(default {DEFAULT_THRESHOLD})")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any regression is flagged "
                         "(default: report-only)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the machine-readable verdict here "
                         "('-' for stdout)")
    ap.add_argument("--bench", default=None, metavar="JSON",
                    help="fresh headline bench JSON (or capture file) "
                         "for the trajectory check")
    ap.add_argument("--history", default=".", metavar="DIR",
                    help="directory holding BENCH_r*.json captures "
                         "(default: .)")
    args = ap.parse_args(argv)

    for d in (args.fresh, args.baseline):
        if not os.path.isdir(d):
            print(f"regress: not a directory: {d}", file=sys.stderr)
            return 2

    verdict = compare_dirs(args.fresh, args.baseline, args.threshold)
    verdict["trajectory"] = trajectory_check(args.bench, args.history,
                                             args.threshold)
    if verdict["trajectory"]["regression"]:
        t = verdict["trajectory"]
        verdict["regressions"].append({
            "file": "BENCH trajectory", "row": t["best_prior"]["capture"],
            "metric": "value", "baseline": t["best_prior"]["value"],
            "fresh": t["fresh"], "ratio": t["ratio"]})
    n_reg = len(verdict["regressions"])
    verdict.update(threshold=args.threshold, strict=args.strict,
                   verdict="fail" if n_reg else "pass")

    compared = sum(f["compared"] for f in verdict["files"].values())
    print(f"regress: {len(verdict['files'])} file(s), {compared} "
          f"metric(s) compared, {n_reg} regression(s), "
          f"{len(verdict['improvements'])} improvement(s) "
          f"[threshold {args.threshold:.0%}]")
    for r in verdict["regressions"]:
        print(f"  REGRESSION {r['file']} [{r['row']}] {r['metric']}: "
              f"{r['baseline']} -> {r['fresh']} ({r['ratio']}x)")
    for r in verdict["improvements"]:
        print(f"  improved   {r['file']} [{r['row']}] {r['metric']}: "
              f"{r['baseline']} -> {r['fresh']} ({r['ratio']}x)")
    if not compared and not n_reg:
        print("  (no overlapping rows — nothing to compare; advisory pass)")

    if args.json == "-":
        json.dump(verdict, sys.stdout, indent=2, default=str)
        sys.stdout.write("\n")
    elif args.json:
        with open(args.json, "w") as f:
            json.dump(verdict, f, indent=2, default=str)

    return 1 if (args.strict and n_reg) else 0


if __name__ == "__main__":
    raise SystemExit(main())
