"""Render captured measurement evidence into one markdown document.

The reference curates every campaign's raw numbers into spreadsheet
tables (``hw/hw2/programming/data/data.ods``, ``hw/hw4/programming/
data.ods``, …) next to the written analyses.  This tool is that layer:
it scans ``bench_results/`` (device CSVs at the root, CPU sweeps under
``cpu/``, batch campaigns under ``jobs/``) and emits ``docs/DATA.md`` —
one table per artifact, headline bench JSONs summarized first — so the
curated view regenerates in one command after every capture:

    python -m cme213_tpu.bench.report [--dir bench_results] [--out docs/DATA.md]
"""

from __future__ import annotations

import argparse
import csv
import json
import os


def _md_table(rows: list[dict]) -> str:
    rows = [r for r in rows if r]
    if not rows:
        return "(empty)\n"
    # column union across rows, first-seen order: heterogeneous rows
    # (e.g. a failure row sorted before a measured row in the tranche-1
    # table) must not hide the measured row's value columns
    cols = list(dict.fromkeys(c for r in rows for c in r))
    out = ["| " + " | ".join(cols) + " |",
           "|" + "|".join("---" for _ in cols) + "|"]
    for r in rows:
        out.append("| " + " | ".join(str(r.get(c, "")) for c in cols) + " |")
    return "\n".join(out) + "\n"


def _read_csv(path: str) -> list[dict]:
    with open(path, newline="") as f:
        return list(csv.DictReader(f))


def _bench_section(path: str, label: str) -> list[str]:
    try:
        with open(path) as f:
            # the bench writes ONE JSON line (possibly after stderr noise
            # in hand-captured files); take the last parseable line
            doc = None
            for line in f:
                line = line.strip()
                if line.startswith("{"):
                    try:
                        doc = json.loads(line)
                    except json.JSONDecodeError:
                        continue
    except OSError:
        return []
    if doc is None:
        return []
    lines = [f"## Headline bench ({label})", ""]
    lines.append(f"- **metric**: {doc.get('metric')}")
    lines.append(f"- **value**: {doc.get('value')} {doc.get('unit')}"
                 f" — {doc.get('vs_baseline')}× the GTX-580 baseline"
                 + (f", {doc.get('pct_hbm_peak')}% of HBM peak"
                    if doc.get("pct_hbm_peak") is not None else "")
                 + (f", {doc.get('bound')}-bound"
                    if doc.get("bound") else ""))
    kernels = doc.get("kernels")
    if kernels:
        lines += ["", _md_table(kernels)]
    lines.append("")
    return lines


def generate(results_dir: str) -> str:
    lines = ["# Measurement data (auto-generated)", "",
             f"Rendered from `{results_dir}/` by "
             "`python -m cme213_tpu.bench.report`; capture context in "
             "`docs/REPORT.md` and `bench_results/cpu/HOST.txt`.", ""]
    for dtype in ("f32", "f64"):
        lines += _bench_section(
            os.path.join(results_dir, f"bench_{dtype}.json"), dtype)

    # first-window banked rows (tpu_tranche1.sh): per-kernel JSON rows
    # committed before the long sweeps — shown even when a later full
    # bench supersedes them, as the capture-provenance record
    tranche = sorted(f for f in (os.listdir(results_dir)
                                 if os.path.isdir(results_dir) else [])
                     if f.startswith("tranche1_") and f.endswith(".json"))
    t_rows = []
    for fname in tranche:
        try:
            with open(os.path.join(results_dir, fname)) as f:
                t_rows.append(json.loads(f.read().strip() or "{}"))
        except (OSError, json.JSONDecodeError):
            continue
    if t_rows:
        lines += ["## First-window banked rows (tranche 1)", "",
                  _md_table(t_rows)]

    sections = [("Device sweeps", results_dir),
                ("CPU-platform sweeps", os.path.join(results_dir, "cpu")),
                ("Batch campaigns", os.path.join(results_dir, "jobs"))]
    for title, d in sections:
        if not os.path.isdir(d):
            continue
        csvs = sorted(f for f in os.listdir(d) if f.endswith(".csv"))
        if not csvs:
            continue
        lines += [f"## {title} (`{os.path.relpath(d)}`)", ""]
        for fname in csvs:
            rows = _read_csv(os.path.join(d, fname))
            lines += [f"### {fname}", ""]
            if "compile_coverage" in fname:
                lines += ["Compile coverage, not a timing table: `ok` "
                          "means the kernel builds and runs under that "
                          "mesh shape; `mode=interpret` rows exercise "
                          "the Pallas interpreter, ~40-80× slower than "
                          "the compiled kernel.", ""]
            lines += [_md_table(rows)]
    # regression-gate verdict (bench/regress.py --json), when banked
    regress = os.path.join(results_dir, "regress.json")
    if os.path.isfile(regress):
        try:
            with open(regress) as f:
                verdict = json.load(f)
        except (OSError, json.JSONDecodeError):
            verdict = None
        if verdict:
            lines += [
                "## Regression gate", "",
                f"- **verdict**: {verdict.get('verdict')} "
                f"(threshold {verdict.get('threshold')})", ""]
            if verdict.get("regressions"):
                lines += [_md_table(verdict["regressions"])]

    smoke = os.path.join(results_dir, "smoke_tpu.txt")
    if os.path.isfile(smoke):
        with open(smoke) as f:
            content = f.read().strip()
        lines += ["## Pallas kernel smoke (on hardware)", "", "```",
                  content, "```", ""]
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="bench_results")
    ap.add_argument("--out", default="docs/DATA.md")
    args = ap.parse_args(argv)
    doc = generate(args.dir)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        f.write(doc)
    print(f"{args.out}: {len(doc.splitlines())} lines")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
