"""Sorts — TPU-resident pipelines.

The reference's hw4 sorts are host-native OpenMP (the C++/OpenMP parity
component lives in ``cme213_tpu/native``); these are the TPU-resident
redesigns promised in SURVEY §7:

- ``radix_sort``   — LSD radix sort with the reference's exact 4-phase pass
  structure (``hw/hw4/programming/radixsort.cpp:22-121``): (1) per-block
  digit histograms, (2+3) exclusive scan over ``(digit, block)`` producing
  per-block scatter bases, (4) stable scatter.  Phases 1-3 are dense
  one-hot reductions and scans (MXU/VPU shapes); the scatter is an XLA
  scatter.  ``num_bits`` and ``block_size`` are the same knobs the reference
  CLI exposes (``radixsort.cpp:163-179``, defaults 8 / 8192... configurable).
- ``bitonic_sort`` — a merge-network sort: the TPU-native analog of hw4's
  recursive merge sort (``mergesort.cpp:31-144``).  The task-tree
  merge becomes a data-parallel bitonic merging network (log² stages of
  vectorized compare-exchange), which is how a "parallel merge sort" is
  expressed for a SIMD machine with no task runtime.
- ``sort`` / ``sort_pairs`` — ``lax.sort`` wrappers (the Thrust-analog
  library path used by hw3 pipelines).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .scan import exclusive_scan


def sort(keys: jnp.ndarray) -> jnp.ndarray:
    return lax.sort(keys)


def sort_auto(keys: jnp.ndarray) -> jnp.ndarray:
    """Sort via the measured winner for this device and size.

    Consults the tuning cache (``core/tune.py``, op ``sort``, shape class
    ``n<canonical>``) for the kernel the last ``tune run`` crowned —
    ``lax`` (the library path), ``radix``, or ``bitonic`` — and falls
    back to ``lax.sort`` with no cached winner or ``CME213_TUNE=0``.
    The dispatch happens at trace time (lengths are static under jit),
    so each shape still compiles exactly one kernel."""
    from ..core import programs, tune

    rec = tune.lookup("sort", f"n{programs.canonical_size(keys.shape[0])}",
                      str(keys.dtype))
    kernel = "lax"
    if rec is not None:
        try:
            kernel = str(rec["statics"].get("kernel", "lax"))
        except (TypeError, AttributeError):
            kernel = "lax"
    if kernel == "radix" and keys.dtype == jnp.uint32:
        return radix_sort(keys)
    if kernel == "bitonic":
        return bitonic_sort(keys)
    return sort(keys)


def sort_pairs(keys: jnp.ndarray, values: jnp.ndarray):
    return lax.sort((keys, values), num_keys=1)


@partial(jax.jit, static_argnames=("num_bits", "block_size", "key_bits"))
def radix_sort(keys: jnp.ndarray, num_bits: int = 8, block_size: int = 8192,
               key_bits: int = 32) -> jnp.ndarray:
    """LSD radix sort of uint32 keys, 4-phase block-decomposed passes.

    Pads to a block multiple with 0xFFFFFFFF sentinels (dropped on return).
    """
    assert keys.dtype == jnp.uint32
    n = keys.shape[0]
    nbuckets = 1 << num_bits
    nblocks = max(1, -(-n // block_size))
    padded = nblocks * block_size
    sentinel = jnp.uint32(0xFFFFFFFF)
    data = jnp.full((padded,), sentinel, jnp.uint32).at[:n].set(keys)

    def one_pass(shift, data):
        blocks = data.reshape(nblocks, block_size)
        digits = ((blocks >> shift) & (nbuckets - 1)).astype(jnp.int32)
        # (1) per-block histograms — one-hot reduction over the block dim
        oh = jax.nn.one_hot(digits, nbuckets, dtype=jnp.int32)  # (B, S, K)
        hist = oh.sum(axis=1)                                   # (B, K)
        # (2)+(3) global exclusive scan in (digit-major, block-minor) order:
        # base[d, b] = start position of digit d's run from block b — the
        # reference's bucket scan + downsweep (radixsort.cpp:75-108).
        bases = exclusive_scan(hist.T.reshape(-1)).reshape(nbuckets, nblocks)
        # (4) stable scatter: rank within block among equal digits
        ranks = jnp.cumsum(oh, axis=1) - 1                      # (B, S, K)
        my_rank = jnp.take_along_axis(ranks, digits[..., None], axis=2)[..., 0]
        my_base = bases[digits, jnp.arange(nblocks)[:, None]]
        pos = (my_base + my_rank).reshape(-1)
        return jnp.zeros_like(data).at[pos].set(data.reshape(-1))

    for shift in range(0, key_bits, num_bits):
        data = one_pass(shift, data)
    return data[:n]


def _bitonic_merge(x: jnp.ndarray, stage_size: int) -> jnp.ndarray:
    """Merge bitonic runs of length ``stage_size`` into sorted runs."""
    n = x.shape[0]
    k = stage_size
    while k >= 2:
        half = k // 2
        v = x.reshape(-1, k)
        lo = v[:, :half]
        hi = v[:, half:]
        new_lo = jnp.minimum(lo, hi)
        new_hi = jnp.maximum(lo, hi)
        x = jnp.concatenate([new_lo, new_hi], axis=1).reshape(n)
        k = half
    return x


@jax.jit
def bitonic_sort(keys: jnp.ndarray) -> jnp.ndarray:
    """Bitonic sorting network over a power-of-2-padded array.

    Each outer stage doubles the sorted-run length (the merge tree of
    mergesort.cpp:76-144, flattened into compare-exchange sweeps); inner
    sweeps are fully vectorized min/max over reshaped views.
    """
    n = keys.shape[0]
    m = 1 << max(1, (n - 1).bit_length())
    if keys.dtype == jnp.uint32:
        pad_val = jnp.uint32(0xFFFFFFFF)
    elif keys.dtype == jnp.int32:
        pad_val = jnp.int32(2**31 - 1)
    else:
        pad_val = jnp.asarray(jnp.inf, keys.dtype)
    x = jnp.full((m,), pad_val, keys.dtype).at[:n].set(keys)

    size = 2
    while size <= m:
        # make runs of `size` bitonic: reverse every other run of size/2
        v = x.reshape(-1, size)
        left = v[:, : size // 2]
        right = v[:, size // 2:][:, ::-1]
        x = jnp.concatenate([left, right], axis=1).reshape(m)
        x = _bitonic_merge(x, size)
        size *= 2
    return x[:n]
