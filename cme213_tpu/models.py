"""Workload-family index (alias module).

The framework's "model families" are the six reference workloads; their
canonical homes are the driver modules in ``cme213_tpu.apps``.  This module
re-exports them under one roof for discoverability.
"""

from .apps import cipher, heat2d, pagerank, sorts, spmv_scan, vigenere

__all__ = ["cipher", "heat2d", "pagerank", "sorts", "spmv_scan", "vigenere"]
