"""ULP-distance float comparison.

Re-expresses the reference's ``AlmostEqual2sComplement`` (two's-complement ULP
trick, ``hw/hw1/programming/mp1-util.h:44-61``; templated float/double variant
``hw/hw2/programming/mp1-util.h:43-76``) as a vectorized numpy operation using
the monotonic unsigned "radix key" transform — the same ordering as the
reference's signed transform but free of signed-overflow corner cases:

    key(x) = bits(x) flipped so that key is monotonic in x over all finite
             floats (sign bit set for positives, all bits flipped for
             negatives).

ULP distance is then plain unsigned subtraction of keys.
"""

from __future__ import annotations

import numpy as np

_FLOAT_VIEWS = {
    np.dtype(np.float32): (np.uint32, np.uint64, 0x8000_0000),
    np.dtype(np.float64): (np.uint64, np.uint64, 0x8000_0000_0000_0000),
}


def _monotonic_key(x: np.ndarray) -> np.ndarray:
    uint_t, wide_t, signbit = _FLOAT_VIEWS[x.dtype]
    bits = x.view(uint_t)
    neg = (bits & uint_t(signbit)) != 0
    key = np.where(neg, ~bits, bits | uint_t(signbit))
    return key.astype(wide_t) if uint_t is not np.uint64 else key


def ulp_distance(a, b) -> np.ndarray:
    """Elementwise ULP distance between two same-dtype float arrays.

    Returned as uint64 (saturating semantics unnecessary: exact for f32; for
    f64 the distance itself fits uint64).
    """
    a = np.asarray(a)
    b = np.asarray(b)
    if a.dtype != b.dtype:
        raise ValueError(f"dtype mismatch: {a.dtype} vs {b.dtype}")
    if a.dtype not in _FLOAT_VIEWS:
        raise ValueError(f"unsupported dtype {a.dtype}")
    ka = _monotonic_key(a)
    kb = _monotonic_key(b)
    return np.where(ka >= kb, ka - kb, kb - ka)


def almost_equal_ulps(a, b, max_ulps: int = 10) -> np.ndarray:
    """Elementwise bool: within ``max_ulps`` ULPs.

    ``max_ulps`` defaults to 10, the reference's checker tolerance
    (``hw/hw1/programming/pagerank.cu:43``, ``hw/hw2/programming/2dHeat.cu``
    ``checkErrors``).  NaNs never compare equal.
    """
    a = np.asarray(a)
    b = np.asarray(b)
    ok = ulp_distance(a, b) <= np.uint64(max_ulps)
    return ok & ~(np.isnan(a) | np.isnan(b))
