"""Guarded execution (ISSUE 5): conformance gating + admission control.

Two prongs, both exercised end-to-end under deterministic injected
faults:

- **Conformance gating** (`core/conformance.py`): a rung whose probe
  diverges from the reference rung is demoted with WRONG_ANSWER before
  it can serve a silently-wrong result; `wrong:<op>` clauses poison
  exactly one probe so the gate is testable on CPU.  Verdicts cache
  in-process and optionally on disk (`CME213_CONFORMANCE_CACHE`).
- **Admission control** (`core/admission.py`): jitted computations are
  preflighted against `CME213_MEMORY_BUDGET`; a runtime
  RESOURCE_EXHAUSTED (`oom:<op>` clauses) halves the solve chunk /
  pipeline tile and retries — bitwise-neutral by construction.
"""

import json
import os

import numpy as np
import pytest

from cme213_tpu.core import (FailureKind, admission, classify_failure,
                             conformance, faults, metrics, trace,
                             with_fallback)


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.clear_events()
    conformance.reset()
    yield
    faults.reset()
    conformance.reset()


# ------------------------------------------------------------ fault clauses

def test_wrong_and_oom_clause_parsing():
    plan = faults.FaultPlan.parse("wrong:spmv_scan:2, oom:heat_chunk")
    kinds = [(c.kind, c.op, c.nth) for c in plan.clauses]
    assert kinds == [("wrong", "spmv_scan", 2), ("oom", "heat_chunk", 1)]


def test_maybe_perturb_fires_on_nth_call_only():
    with faults.injected("wrong:op:2"):
        a = np.ones(8, np.float32)
        out1 = faults.maybe_perturb("op", a)
        np.testing.assert_array_equal(out1, a)      # call 1: clean
        out2 = faults.maybe_perturb("op", a)        # call 2: perturbed
        assert out2[0] != a[0] and np.isfinite(out2).all()
        np.testing.assert_array_equal(out2[1:], a[1:])  # ONE element
        np.testing.assert_array_equal(a, np.ones(8, np.float32))  # no mutation
        out3 = faults.maybe_perturb("op", a)
        np.testing.assert_array_equal(out3, a)      # call 3: clean again


def test_wrong_and_oom_are_incarnation_gated(monkeypatch):
    monkeypatch.setenv("CME213_INCARNATION", "1")
    with faults.injected("wrong:op:1, oom:op:1"):
        a = np.ones(4, np.float32)
        np.testing.assert_array_equal(faults.maybe_perturb("op", a), a)
        faults.maybe_oom("op")  # must not raise on a restarted incarnation


def test_maybe_oom_raises_resource_classified():
    with faults.injected("oom:op:1"):
        with pytest.raises(faults.InjectedResourceExhausted) as ei:
            faults.maybe_oom("op")
    assert classify_failure(ei.value) is FailureKind.RESOURCE


def test_real_resource_exhausted_message_classifies():
    exc = RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to "
                       "allocate 17179869184 bytes.")
    assert classify_failure(exc) is FailureKind.RESOURCE
    # compile-time VMEM pressure stays COMPILE (a different kernel
    # formulation can fix it; smaller chunks cannot)
    assert (classify_failure(RuntimeError("Mosaic: vmem limit exceeded"))
            is FailureKind.COMPILE)


# ------------------------------------------------------- conformance core

def test_conformance_check_pass_fail_and_events():
    ref = np.arange(8, dtype=np.float32)
    v = conformance.check("op", "good", "f32", lambda: ref.copy(),
                          lambda: ref.copy())
    assert v.ok and not v.cached and v.detail == "bitwise"
    bad = ref.copy()
    bad[3] += 1.0
    v2 = conformance.check("op", "bad", "f32", lambda: bad,
                           lambda: ref.copy())
    assert not v2.ok
    failed = trace.events("conformance-failed")
    assert [(e["op"], e["rung"]) for e in failed] == [("op", "bad")]
    probes = trace.events("conformance-probe")
    assert [e["ok"] for e in probes] == [True, False]


def test_conformance_declared_tolerance():
    ref = np.ones(1000, np.float32)
    near = ref * np.float32(1 + 1e-7)
    assert not conformance.check("op", "r1", "f32", lambda: near,
                                 lambda: ref.copy()).ok  # bitwise default
    assert conformance.check("op", "r2", "f32", lambda: near,
                             lambda: ref.copy(), rel_l2=1e-5).ok
    far = ref * np.float32(1.5)
    assert not conformance.check("op", "r3", "f32", lambda: far,
                                 lambda: ref.copy(), rel_l2=1e-5).ok


def test_conformance_nonfinite_candidate_fails():
    ref = np.ones(4, np.float32)
    bad = ref.copy()
    bad[0] = np.nan
    assert not conformance.check("op", "r", "f32", lambda: bad,
                                 lambda: ref.copy(), rel_l2=1.0).ok


def test_probe_cache_hit_and_miss():
    calls = []

    def candidate():
        calls.append(1)
        return np.ones(4, np.float32)

    ref = lambda: np.ones(4, np.float32)  # noqa: E731
    v1 = conformance.check("op", "r", "cls", candidate, ref)
    v2 = conformance.check("op", "r", "cls", candidate, ref)
    assert len(calls) == 1 and not v1.cached and v2.cached and v2.ok
    # a different shape class is a different verdict: probe re-runs
    conformance.check("op", "r", "other-cls", candidate, ref)
    assert len(calls) == 2
    conformance.reset()
    conformance.check("op", "r", "cls", candidate, ref)
    assert len(calls) == 3


def test_disk_cache_round_trip(tmp_path, monkeypatch):
    path = tmp_path / "verdicts.json"
    monkeypatch.setenv(conformance.CACHE_ENV, str(path))
    calls = []

    def candidate():
        calls.append(1)
        return np.ones(4, np.float32)

    ref = lambda: np.ones(4, np.float32)  # noqa: E731
    conformance.check("op", "r", "cls", candidate, ref)
    assert json.loads(path.read_text())["op|r|cls"]["ok"] is True
    conformance.reset()  # a "new process": in-memory verdicts gone
    v = conformance.check("op", "r", "cls", candidate, ref)
    assert v.ok and v.cached and len(calls) == 1  # served from disk


def test_with_fallback_gate_demotes_wrong_answer():
    res = with_fallback("op", [("a", lambda: "a-val"), ("b", lambda: "b-val")],
                        gate=lambda rung: rung != "a")
    assert res.value == "b-val" and res.rung == "b"
    assert [f.kind for f in res.failures] == [FailureKind.WRONG_ANSWER]
    ev = trace.events("rung-failed")[-1]
    assert ev["kind"] == "wrong_answer" and ev["error"] == "ConformanceFailed"


def test_with_fallback_gate_all_rungs_rejected_raises():
    from cme213_tpu.core import FrameworkError

    with pytest.raises(FrameworkError, match="rungs"):
        with_fallback("op", [("a", lambda: 1)], gate=lambda r: False)


# ---------------------------------------------------------- admission core

def test_parse_budget_suffixes():
    assert admission.parse_budget("1024") == 1024
    assert admission.parse_budget("4K") == 4096
    assert admission.parse_budget("2m") == 2 << 20
    assert admission.parse_budget("1.5G") == int(1.5 * (1 << 30))


def test_preflight_against_fake_budget(monkeypatch):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(a):
        return a * 2.0

    big = jnp.ones((1 << 14,), jnp.float32)  # 64 KiB in + 64 KiB out
    monkeypatch.setenv(admission.BUDGET_ENV, "16K")
    d = admission.preflight(f, big, op="toy")
    assert not d.admitted and d.required_bytes > d.budget_bytes
    ev = trace.events("admission-rejected")[-1]
    assert ev["op"] == "toy" and ev["requested_bytes"] == d.required_bytes
    monkeypatch.setenv(admission.BUDGET_ENV, "64M")
    assert admission.preflight(f, big, op="toy").admitted


def test_preflight_without_budget_is_pass_open(monkeypatch):
    import jax

    monkeypatch.delenv(admission.BUDGET_ENV, raising=False)
    d = admission.preflight(jax.jit(lambda a: a + 1), np.ones(4, np.float32),
                            op="toy")
    # CPU backend reports no device memory: admission stays off
    assert d.admitted and d.budget_bytes is None


def test_admit_chunk_halves_until_fit():
    seen = []

    def pf(k):
        seen.append(k)
        return admission.Decision(k <= 4, k, 4, f"k={k}")

    assert admission.admit_chunk("toy", 16, pf) == 4
    assert seen == [16, 8, 4]
    assert len(trace.events("chunk-shrunk")) == 2


def test_admit_chunk_floor_still_over_budget_raises():
    def pf(k):
        return admission.Decision(False, k, 0, "never fits")

    with pytest.raises(admission.AdmissionError):
        admission.admit_chunk("toy", 8, pf, floor=2)


# --------------------------------------------------- end-to-end: SpMV-scan

def test_spmv_wrong_fault_demotes_and_matches_reference_bitwise():
    """ISSUE-5 acceptance: CME213_FAULTS=wrong:spmv_scan:1 -> the
    conformance gate demotes the poisoned rung and the served result is
    bitwise-equal to the un-faulted reference(-rung) run."""
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(1024, 32, 31, iters=4, seed=0)
    with faults.injected("wrong:spmv_scan:1"):
        out = sp.run_spmv_scan(prob, kernel="blocked")
    served = trace.events("served")[-1]
    assert served["rung"] == "flat" and served["demoted"]
    failed = trace.events("rung-failed")[-1]
    assert failed["rung"] == "blocked" and failed["kind"] == "wrong_answer"
    assert trace.events("conformance-failed")
    faults.reset()
    conformance.reset()
    ref = sp.run_spmv_scan(prob, kernel="flat")
    np.testing.assert_array_equal(out, ref)


def test_spmv_unfaulted_rungs_pass_their_probes():
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(1024, 32, 31, iters=4, seed=0)
    out = sp.run_spmv_scan(prob, kernel="blocked")
    served = trace.events("served")[-1]
    assert served["rung"] == "blocked" and not served["demoted"]
    assert not trace.events("conformance-failed")
    # steady state: the verdict is cached, no further probes
    n_probes = len(trace.events("conformance-probe"))
    sp.run_spmv_scan(prob, kernel="blocked")
    assert len(trace.events("conformance-probe")) == n_probes


def test_spmv_checkpointed_oom_shrinks_chunk_bitwise_equal(tmp_path):
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(1024, 32, 31, iters=8, seed=0)
    with faults.injected("oom:spmv_scan_chunk:1"):
        out_f = sp.run_spmv_scan_checkpointed(
            prob, str(tmp_path / "f.npz"), every=4)
    ev = trace.events("chunk-shrunk")[-1]
    assert (ev["from_size"], ev["to_size"]) == (4, 2)
    faults.reset()
    out_c = sp.run_spmv_scan_checkpointed(
        prob, str(tmp_path / "c.npz"), every=4)
    np.testing.assert_array_equal(out_f, out_c)


# -------------------------------------------------------- end-to-end: heat

def test_heat_checkpointed_oom_shrinks_chunk_bitwise_equal(tmp_path):
    """ISSUE-5 acceptance: CME213_FAULTS=oom:heat_chunk:1 -> the
    checkpointed solve shrinks its chunk, retries, and completes
    bitwise-equal to the un-faulted run."""
    from cme213_tpu.apps.heat2d import run_heat_checkpointed
    from cme213_tpu.config import SimParams

    p = SimParams(nx=24, ny=24, order=2, iters=8)
    with faults.injected("oom:heat_chunk:1"):
        out_f = run_heat_checkpointed(p, str(tmp_path / "f.npz"), every=4)
    ev = trace.events("chunk-shrunk")[-1]
    assert (ev["op"], ev["from_size"], ev["to_size"]) == ("heat2d", 4, 2)
    faults.reset()
    out_c = run_heat_checkpointed(p, str(tmp_path / "c.npz"), every=4)
    np.testing.assert_array_equal(out_f, out_c)


def test_heat_resilient_gate_demotes_diverging_orders():
    """On this backend the order-8 Pallas pipeline rungs bitwise-diverge
    from the XLA reference (FMA contraction on the roll formulation's
    concat seams — docs/resilience.md "Guarded execution"); the gate must
    keep them out of the serving ladder and the served result must be
    bitwise-equal to run_heat.  Order 2 probes clean and serves the
    pipeline rung."""
    import jax.numpy as jnp

    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops import run_heat
    from cme213_tpu.ops.stencil_pipeline import run_heat_resilient

    for order, expect_serving in ((2, "pipeline"), (8, "xla")):
        p = SimParams(nx=40, ny=40, order=order, iters=4)
        u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
        res = run_heat_resilient(jnp.array(u0), 4, order, p.xcfl, p.ycfl,
                                 p.bc, k=1, interpret=True)
        assert res.rung == expect_serving, (order, res.rung)
        ref = np.asarray(run_heat(jnp.array(u0), 4, order, p.xcfl, p.ycfl))
        np.testing.assert_array_equal(np.asarray(res.value), ref)
    assert all(f.kind is FailureKind.WRONG_ANSWER
               for f in res.failures)  # the order-8 demotions


def test_heat_resilient_oom_shrinks_tile():
    import jax.numpy as jnp

    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops import run_heat
    from cme213_tpu.ops.stencil_pipeline import run_heat_resilient

    p = SimParams(nx=40, ny=40, order=2, iters=4)
    u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
    with faults.injected("oom:heat.pipeline:1"):
        res = run_heat_resilient(jnp.array(u0), 4, 2, p.xcfl, p.ycfl, p.bc,
                                 k=1, tile_y=32, interpret=True)
    assert res.rung == "pipeline" and not res.demoted
    ev = trace.events("chunk-shrunk")[-1]
    assert ev["op"] == "heat.pipeline"
    assert (ev["from_size"], ev["to_size"]) == (32, 16)
    ref = np.asarray(run_heat(jnp.array(u0), 4, 2, p.xcfl, p.ycfl))
    np.testing.assert_array_equal(np.asarray(res.value), ref)


def test_pick_pipeline_tile_respects_memory_budget(monkeypatch):
    from cme213_tpu.ops.stencil_pipeline import pick_pipeline_tile

    unclamped = pick_pipeline_tile(4008, 1, 8, target=256, width=1024)
    monkeypatch.setenv(admission.BUDGET_ENV, "1M")
    clamped = pick_pipeline_tile(4008, 1, 8, target=256, width=1024)
    assert clamped < unclamped
    # still a multiple of the halo quantum, still at least one quantum
    assert clamped % 8 == 0 and clamped >= 8
    W = 1024
    assert 2 * 4 * W * (2 * clamped + 2 * 8) <= 1 << 20


# -------------------------------------------------- end-to-end: dist paths

def test_dist_scan_wrong_fault_demotes_ring_to_gather():
    from cme213_tpu.dist import make_mesh_1d
    from cme213_tpu.dist.scan import make_iterated_sharded_scan_gated

    _, mode = make_iterated_sharded_scan_gated(make_mesh_1d(4))
    assert mode == "ring"
    conformance.reset()
    trace.clear_events()
    with faults.injected("wrong:dist_scan:1"):
        _, mode = make_iterated_sharded_scan_gated(make_mesh_1d(4))
    assert mode == "gather"
    ev = trace.events("rung-failed")[-1]
    assert ev["op"] == "dist_scan" and ev["rung"] == "ring"
    assert ev["kind"] == "wrong_answer"


def test_dist_heat_gate_demotes_multistep_at_order8():
    """The k>1 communication-avoiding path bitwise-diverges from the
    exchange-every-step path at order 8 on this backend; the gated solve
    must serve the k=1 result instead."""
    from cme213_tpu.config import SimParams
    from cme213_tpu.dist import make_mesh_1d
    from cme213_tpu.dist.heat import run_distributed_heat

    p = SimParams(nx=64, ny=64, order=8, iters=8)
    mesh = make_mesh_1d(4)
    base = run_distributed_heat(p, mesh, overlap=False, conformance=False)
    multi = run_distributed_heat(p, mesh, overlap=False,
                                 steps_per_exchange=4)
    assert any(e["rung"] == "xla-k4" for e in trace.events("rung-failed"))
    np.testing.assert_array_equal(multi, base)


def test_dist_heat_gated_pallas_serves_conformant_kernel():
    """The Pallas local kernel agrees bitwise with the dist XLA rung (its
    actual contract); the gated path must serve it without demotion and
    match the ungated XLA solve."""
    from cme213_tpu.config import SimParams
    from cme213_tpu.dist import make_mesh_1d
    from cme213_tpu.dist.heat import run_distributed_heat

    p = SimParams(nx=40, ny=48, order=8, iters=4)
    mesh = make_mesh_1d(4)
    out = run_distributed_heat(p, mesh, local_kernel="pallas")
    assert not [e for e in trace.events("rung-failed")
                if e["op"] == "dist_heat"]
    ref = run_distributed_heat(p, mesh, overlap=False, conformance=False)
    np.testing.assert_array_equal(out, ref)


# --------------------------------------------------------------- trace CLI

def test_trace_summary_reports_conformance_and_admission(tmp_path, capsys):
    from cme213_tpu import trace_cli

    recs = [
        {"event": "conformance-probe", "t": 1.0, "op": "spmv_scan",
         "rung": "blocked", "shape_class": "float32", "ok": False,
         "ms": 3.2},
        {"event": "conformance-failed", "t": 1.1, "op": "spmv_scan",
         "rung": "blocked", "shape_class": "float32",
         "detail": "rel_l2=2.5e-01 (tol 1e-05)"},
        {"event": "admission-rejected", "t": 1.2, "op": "heat2d",
         "requested_bytes": 2048, "budget_bytes": 1024,
         "detail": "footprint 2048 > budget 1024"},
        {"event": "chunk-shrunk", "t": 1.3, "op": "heat2d", "from_size": 4,
         "to_size": 2, "reason": "InjectedResourceExhausted"},
    ]
    p = tmp_path / "t.jsonl"
    with open(p, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    assert trace_cli.main(["summary", str(p)]) == 0
    out = capsys.readouterr().out
    assert "conformance: 1 probe(s), 0 passed, 1 failed" in out
    assert "spmv_scan.blocked: FAIL x1" in out
    assert "admission: 1 rejected, 1 chunk(s)/tile(s) shrunk" in out
    assert "heat2d 4 -> 2" in out
    # --require accepts event names (the faultcheck conformance gate)
    assert trace_cli.main(["summary", str(p),
                           "--require", "conformance-failed"]) == 0
    assert trace_cli.main(["summary", str(p),
                           "--require", "epoch-commit"]) == 1


def test_guarded_events_validate_against_schema():
    ref = np.ones(4, np.float32)
    bad = ref + 1
    conformance.check("op", "r", "cls", lambda: bad, lambda: ref.copy())
    with faults.injected("oom:op:1"):
        with pytest.raises(faults.InjectedResourceExhausted):
            faults.maybe_oom("op")
    with faults.injected("wrong:op:1"):
        faults.maybe_perturb("op", np.ones(3, np.float32))

    def pf(k):
        return admission.Decision(k <= 1, k, 1, "d")

    admission.admit_chunk("toy", 2, pf)
    for rec in trace.events():
        assert trace.validate_record(rec) == [], rec


# ------------------------------------------------------------ matrix market

def test_truncated_mtx_to_zero_entries_is_warning_free(tmp_path):
    """np.loadtxt's empty-input UserWarning must not leak: truncation to
    zero entries flows through the DataValidationError path instead."""
    import warnings

    from cme213_tpu.apps.matrix_market import read_matrix_market
    from cme213_tpu.core import DataValidationError

    p = tmp_path / "t.mtx"
    p.write_text("%%MatrixMarket matrix coordinate real general\n3 3 2\n")
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any warning fails the test
        with pytest.raises(DataValidationError, match="entry-count"):
            read_matrix_market(str(p))
