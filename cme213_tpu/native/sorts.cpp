// Host-native multicore sorts — the hw4 parity component.
//
// The reference's hw4 workloads are host-CPU-native OpenMP programs
// (mergesort.cpp, radixsort.cpp); this library provides freshly-designed
// equivalents with the same algorithmic structure and tuning knobs:
//
//  - merge_sort_omp: recursive fork-join task tree (omp task/taskwait) with
//    a serial std::sort leaf below `sort_threshold`, and a parallel merge
//    that splits the larger run at its median and binary-searches the split
//    point in the other run (cf. hw/hw4/programming/mergesort.cpp:31-144 —
//    same strategy, clean two-buffer alternation instead of the reference's
//    parity bookkeeping).
//  - radix_sort_omp: LSD radix sort, `num_bits` per pass, with the classic
//    4-phase block-decomposed pass: parallel per-block histograms, a
//    bucket-major exclusive scan producing per-block scatter bases, and a
//    parallel stable scatter (cf. hw/hw4/programming/radixsort.cpp:22-121).
//  - radix_sort_serial: the serial histogram/scan/scatter baseline
//    (radixsort.cpp:123-161 analog).
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

#include <omp.h>

extern "C" {

int omp_thread_count() { return omp_get_max_threads(); }
void set_omp_threads(int n) { omp_set_num_threads(n); }
double wtime_now() { return omp_get_wtime(); }

// Host-CPU baselines: OpenMP reduction sum and SAXPY (the canonical
// parallel-for kernels; CPU counterpart of ops/elementwise.py's device ops).
double parallel_sum_omp(const float* x, long n) {
  double acc = 0.0;
#pragma omp parallel for reduction(+ : acc) schedule(static)
  for (long i = 0; i < n; ++i) acc += x[i];
  return acc;
}

void saxpy_omp(float alpha, const float* x, float* y, long n) {
#pragma omp parallel for schedule(static)
  for (long i = 0; i < n; ++i) y[i] = alpha * x[i] + y[i];
}

}  // extern "C"

namespace {

// ---------------------------------------------------------------- merge sort

void parallel_merge(const int32_t* a, long na, const int32_t* b, long nb,
                    int32_t* out, long merge_threshold) {
  if (na + nb <= merge_threshold) {
    std::merge(a, a + na, b, b + nb, out);
    return;
  }
  // split the larger run at its midpoint; binary-search the matching split
  // point in the smaller run so both halves merge independently
  if (na < nb) {
    std::swap(a, b);
    std::swap(na, nb);
  }
  long ma = na / 2;
  long mb = std::upper_bound(b, b + nb, a[ma]) - b;
#pragma omp task
  parallel_merge(a, ma, b, mb, out, merge_threshold);
#pragma omp task
  parallel_merge(a + ma, na - ma, b + mb, nb - mb, out + ma + mb,
                 merge_threshold);
#pragma omp taskwait
}

// Sorts a[0..n); result lands in `a` if !into_tmp, else in `tmp`.
void msort_rec(int32_t* a, int32_t* tmp, long n, bool into_tmp,
               long sort_threshold, long merge_threshold) {
  if (n <= sort_threshold) {
    std::sort(a, a + n);
    if (into_tmp) std::memcpy(tmp, a, n * sizeof(int32_t));
    return;
  }
  long mid = n / 2;
  // halves must land in the buffer we merge FROM, i.e. the other one
#pragma omp task
  msort_rec(a, tmp, mid, !into_tmp, sort_threshold, merge_threshold);
#pragma omp task
  msort_rec(a + mid, tmp + mid, n - mid, !into_tmp, sort_threshold,
            merge_threshold);
#pragma omp taskwait
  if (into_tmp) {
    parallel_merge(a, mid, a + mid, n - mid, tmp, merge_threshold);
  } else {
    parallel_merge(tmp, mid, tmp + mid, n - mid, a, merge_threshold);
  }
}

// ---------------------------------------------------------------- radix sort

void radix_pass_parallel(const uint32_t* in, uint32_t* out, long n, int shift,
                         int num_bits, long block_size) {
  const long nbuckets = 1L << num_bits;
  const uint32_t mask = static_cast<uint32_t>(nbuckets - 1);
  const long nblocks = (n + block_size - 1) / block_size;

  // phase 1: per-block histograms (hist[block][bucket])
  std::vector<long> hist(nblocks * nbuckets, 0);
#pragma omp parallel for schedule(static)
  for (long blk = 0; blk < nblocks; ++blk) {
    long lo = blk * block_size;
    long hi = std::min(n, lo + block_size);
    long* h = &hist[blk * nbuckets];
    for (long i = lo; i < hi; ++i) h[(in[i] >> shift) & mask]++;
  }

  // phases 2+3: bucket-major exclusive scan over (bucket, block) — the
  // cross-block reduction + downsweep producing per-block scatter bases
  std::vector<long> base(nblocks * nbuckets);
  long running = 0;
  for (long d = 0; d < nbuckets; ++d) {
    for (long blk = 0; blk < nblocks; ++blk) {
      base[blk * nbuckets + d] = running;
      running += hist[blk * nbuckets + d];
    }
  }

  // phase 4: parallel stable scatter — each block owns its `base` slice,
  // which is dead after this phase, so it doubles as the scatter cursor
  // (no per-thread 2^num_bits stack/heap copy)
#pragma omp parallel for schedule(static)
  for (long blk = 0; blk < nblocks; ++blk) {
    long lo = blk * block_size;
    long hi = std::min(n, lo + block_size);
    long* cursor = &base[blk * nbuckets];
    for (long i = lo; i < hi; ++i) {
      uint32_t d = (in[i] >> shift) & mask;
      out[cursor[d]++] = in[i];
    }
  }
}

void radix_pass_serial(const uint32_t* in, uint32_t* out, long n, int shift,
                       int num_bits) {
  const long nbuckets = 1L << num_bits;
  const uint32_t mask = static_cast<uint32_t>(nbuckets - 1);
  std::vector<long> count(nbuckets, 0);
  for (long i = 0; i < n; ++i) count[(in[i] >> shift) & mask]++;
  long running = 0;
  for (long d = 0; d < nbuckets; ++d) {
    long c = count[d];
    count[d] = running;
    running += c;
  }
  for (long i = 0; i < n; ++i) {
    uint32_t d = (in[i] >> shift) & mask;
    out[count[d]++] = in[i];
  }
}

}  // namespace

extern "C" {

void merge_sort_omp(int32_t* data, int32_t* scratch, long n,
                    long sort_threshold, long merge_threshold) {
  if (sort_threshold < 32) sort_threshold = 32;
  if (merge_threshold < 32) merge_threshold = 32;
#pragma omp parallel
#pragma omp single
  msort_rec(data, scratch, n, /*into_tmp=*/false, sort_threshold,
            merge_threshold);
}

void radix_sort_omp(uint32_t* data, uint32_t* scratch, long n, int num_bits,
                    long block_size) {
  if (num_bits < 1) num_bits = 8;
  if (num_bits > 16) num_bits = 16;
  if (block_size < 1) block_size = 8192;
  uint32_t* src = data;
  uint32_t* dst = scratch;
  for (int shift = 0; shift < 32; shift += num_bits) {
    radix_pass_parallel(src, dst, n, shift, num_bits, block_size);
    std::swap(src, dst);
  }
  if (src != data) std::memcpy(data, src, n * sizeof(uint32_t));
}

void radix_sort_serial(uint32_t* data, uint32_t* scratch, long n,
                       int num_bits) {
  if (num_bits < 1) num_bits = 8;
  if (num_bits > 16) num_bits = 16;
  uint32_t* src = data;
  uint32_t* dst = scratch;
  for (int shift = 0; shift < 32; shift += num_bits) {
    radix_pass_serial(src, dst, n, shift, num_bits);
    std::swap(src, dst);
  }
  if (src != data) std::memcpy(data, src, n * sizeof(uint32_t));
}

}  // extern "C"
