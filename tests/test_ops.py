import jax.numpy as jnp
import numpy as np
import pytest

from cme213_tpu.ops import (
    bitonic_sort,
    blocked_inclusive_scan,
    exclusive_scan,
    histogram_onehot,
    histogram_segment,
    histogram_sort,
    inclusive_scan,
    radix_sort,
    segment_ids_from_starts,
    segmented_scan_from_starts,
    sort,
    sort_pairs,
    validate_segments,
)
from cme213_tpu.verify import golden


@pytest.fixture
def rng():
    return np.random.default_rng(42)


# ---------- scans ----------

def test_inclusive_exclusive_scan(rng):
    x = rng.integers(0, 100, 257).astype(np.int32)
    inc = np.asarray(inclusive_scan(jnp.asarray(x)))
    exc = np.asarray(exclusive_scan(jnp.asarray(x)))
    np.testing.assert_array_equal(inc, np.cumsum(x))
    np.testing.assert_array_equal(exc, np.cumsum(x) - x)


def test_blocked_scan_matches_flat(rng):
    x = rng.integers(0, 10, 1024).astype(np.int32)
    out = np.asarray(blocked_inclusive_scan(jnp.asarray(x), block_size=64))
    np.testing.assert_array_equal(out, np.cumsum(x))


# ---------- segmented scan ----------

def _random_segments(rng, n, p):
    starts = np.sort(rng.choice(np.arange(1, n), size=p - 1, replace=False))
    return np.concatenate([[0], starts]).astype(np.int32)


def test_segment_ids(rng):
    s = np.array([0, 3, 7], dtype=np.int32)
    ids = np.asarray(segment_ids_from_starts(jnp.asarray(s), 10))
    np.testing.assert_array_equal(ids, [0, 0, 0, 1, 1, 1, 1, 2, 2, 2])


def test_segmented_scan_matches_golden(rng):
    n, p = 1000, 37
    s = _random_segments(rng, n, p)
    v = rng.standard_normal(n).astype(np.float32)
    ref = golden.host_segmented_scan(v, s)
    out = np.asarray(segmented_scan_from_starts(jnp.asarray(v), jnp.asarray(s)))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_segmented_scan_single_segment(rng):
    v = rng.standard_normal(64).astype(np.float32)
    s = np.array([0], dtype=np.int32)
    out = np.asarray(segmented_scan_from_starts(jnp.asarray(v), jnp.asarray(s)))
    np.testing.assert_allclose(out, np.cumsum(v), rtol=1e-5, atol=1e-5)


def test_segmented_scan_dense_matches(rng):
    from cme213_tpu.ops.segmented import segmented_scan_dense

    n, p = 300, 20
    s = _random_segments(rng, n, p)
    v = rng.standard_normal(n).astype(np.float32)
    max_len = int(np.diff(np.concatenate([s, [n]])).max())
    ref = golden.host_segmented_scan(v, s)
    out = np.asarray(segmented_scan_dense(jnp.asarray(v), jnp.asarray(s),
                                          max_len))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_validate_segments():
    validate_segments(np.array([0, 5, 9]), 12)
    with pytest.raises(ValueError):
        validate_segments(np.array([1, 5]), 12)      # s[0] != 0
    with pytest.raises(ValueError):
        validate_segments(np.array([0, 5, 5]), 12)   # not strictly increasing
    with pytest.raises(ValueError):
        validate_segments(np.array([0, 15]), 12)     # beyond end


# ---------- histograms ----------

@pytest.mark.parametrize("fn", [histogram_sort, histogram_onehot, histogram_segment])
def test_histograms_match_numpy(rng, fn):
    x = rng.integers(0, 26, 5000).astype(np.int32)
    ref = np.bincount(x, minlength=26)
    out = np.asarray(fn(jnp.asarray(x), 26))
    np.testing.assert_array_equal(out, ref)


# ---------- sorts ----------

def test_lax_sort_wrappers(rng):
    x = rng.integers(0, 2**31, 1000).astype(np.uint32)
    np.testing.assert_array_equal(np.asarray(sort(jnp.asarray(x))), np.sort(x))
    k, v = sort_pairs(jnp.asarray(x), jnp.arange(1000))
    np.testing.assert_array_equal(np.asarray(k), np.sort(x))
    np.testing.assert_array_equal(x[np.asarray(v)], np.sort(x))


@pytest.mark.parametrize("n", [100, 8192, 10000])
def test_radix_sort(rng, n):
    x = rng.integers(0, 2**32, n, dtype=np.uint64).astype(np.uint32)
    out = np.asarray(radix_sort(jnp.asarray(x), num_bits=8, block_size=2048))
    np.testing.assert_array_equal(out, np.sort(x))


def test_radix_sort_4bit(rng):
    x = rng.integers(0, 2**32, 3000, dtype=np.uint64).astype(np.uint32)
    out = np.asarray(radix_sort(jnp.asarray(x), num_bits=4, block_size=512))
    np.testing.assert_array_equal(out, np.sort(x))


@pytest.mark.parametrize("n", [1, 2, 100, 1024, 1000])
def test_bitonic_sort(rng, n):
    x = rng.integers(0, 2**31, n).astype(np.uint32)
    out = np.asarray(bitonic_sort(jnp.asarray(x)))
    np.testing.assert_array_equal(out, np.sort(x))


def test_bitonic_sort_float(rng):
    x = rng.standard_normal(500).astype(np.float32)
    out = np.asarray(bitonic_sort(jnp.asarray(x)))
    np.testing.assert_array_equal(out, np.sort(x))
