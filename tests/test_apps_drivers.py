"""Smoke tests for the workload drivers (L5) and sweep harness (L7)."""

import numpy as np
import pytest

from cme213_tpu.apps.cipher import make_corpus, run_cipher
from cme213_tpu.apps.heat2d import run_single, run_distributed
from cme213_tpu.apps.sorts import run_merge_sort, run_radix_sort
from cme213_tpu.bench import (
    cipher_vector_length_sweep,
    heat_sweep,
    pagerank_avg_edges_sweep,
    sort_thread_sweep,
    spmv_suite_sweep,
    write_csv,
)
from cme213_tpu.config import SimParams


def test_cipher_driver():
    assert run_cipher(make_corpus(1 << 12, seed=1), shift=5, replicate=2)


def test_heat2d_driver(tmp_path):
    p = SimParams(nx=40, ny=40, order=8, iters=5)
    res = run_single(p, check_cpu=True, save_files=True, out_dir=str(tmp_path))
    assert res.ok
    assert (tmp_path / "grid_init.txt").exists()
    assert (tmp_path / "grid_final_gpu_global.txt").exists()
    assert (tmp_path / "grid_final_gpu_shared.txt").exists()


def test_heat2d_distributed_driver(tmp_path):
    from cme213_tpu.config import GridMethod

    p = SimParams(nx=16, ny=16, order=2, iters=3,
                  grid_method=GridMethod.BLOCKS_2D, synchronous=False)
    out = run_distributed(p, num_devices=4, save_files=True,
                          out_dir=str(tmp_path))
    assert np.isfinite(out).all()
    assert (tmp_path / "grid_final.txt").exists()
    # per-rank dumps (4 ranks on the 2x2 mesh)
    for r in range(4):
        assert (tmp_path / f"grid{r}_final.txt").exists()


def test_sorts_driver():
    assert run_merge_sort(50_000)
    assert run_radix_sort(50_000, tpu=True)


def test_cipher_sweep_csv(tmp_path):
    rows = cipher_vector_length_sweep(steps=2, max_bytes=1 << 16)
    assert len(rows) == 2 and "uint2_gbs" in rows[0]
    f = tmp_path / "c.csv"
    write_csv(rows, str(f))
    assert f.read_text().count("\n") == 3


def test_pagerank_sweep():
    rows = pagerank_avg_edges_sweep(num_nodes=2048, edges_range=range(2, 4),
                                    iterations=4)
    assert [r["avg_edges"] for r in rows] == [2, 3]
    assert all(r["gbs"] > 0 for r in rows)


def test_heat_sweep():
    rows = heat_sweep(sizes=(32,), orders=(2,), iters=4, ks=(1, 2))
    assert {r["kernel"] for r in rows} == {"xla", "pipeline-k1",
                                           "pipeline-k2"}
    assert all(r["dtype"] == "f32" for r in rows)


def test_sort_thread_sweep():
    rows = sort_thread_sweep(num_elements=20_000, threads=(1, 2))
    assert len(rows) == 2


def test_spmv_suite_sweep():
    rows = spmv_suite_sweep(names=["jonheart", "dense2"], scale=0.01)
    assert len(rows) == 2
    assert all(float(r["rel_l2"]) < 1e-3 for r in rows)


def test_transfer_bandwidth_sweep():
    from cme213_tpu.bench import transfer_bandwidth_sweep

    rows = transfer_bandwidth_sweep(sizes=(1 << 16,))
    assert rows[0]["h2d_gbs"] > 0 and rows[0]["d2h_gbs"] > 0


def test_pallas_tile_sweep():
    from cme213_tpu.bench import pallas_tile_sweep

    rows = pallas_tile_sweep(size=32, order=2, iters=2, tiles=(8, 16, 5))
    # 5 doesn't divide 32 → skipped
    assert [r["tile_y"] for r in rows] == [8, 16]


def test_dist_heat_sweep():
    from cme213_tpu.bench import dist_heat_sweep

    rows = dist_heat_sweep(size=16, order=2, iters=4, ndevs=(1, 2))
    # 2 devices × 2 methods × 3 schemes (sync, async, comm-avoiding)
    assert len(rows) == 12
    assert {r["requested"] for r in rows} == {"sync", "async", "ca-k4"}
    assert {r["scheme"] for r in rows} == {"sync", "async", "ca-k4"}


def test_heat_checkpoint_resume_integration(tmp_path):
    """Interrupt-and-resume equals an uninterrupted solve."""
    import jax.numpy as jnp

    from cme213_tpu.core.checkpoint import run_with_checkpoints
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops import run_heat

    p = SimParams(nx=20, ny=20, order=4, iters=12)
    u0 = make_initial_grid(p)

    def step(state, k):
        return run_heat(jnp.asarray(state), k, p.order, p.xcfl, p.ycfl)

    ck = str(tmp_path / "heat.npz")
    out = run_with_checkpoints(step, np.asarray(u0), 12, ck, every=5)
    ref = np.asarray(run_heat(jnp.array(u0), 12, p.order, p.xcfl, p.ycfl))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6, atol=1e-7)
