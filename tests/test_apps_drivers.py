"""Smoke tests for the workload drivers (L5) and sweep harness (L7)."""

import numpy as np
import pytest

from cme213_tpu.apps.cipher import make_corpus, run_cipher
from cme213_tpu.apps.heat2d import run_single, run_distributed
from cme213_tpu.apps.sorts import run_merge_sort, run_radix_sort
from cme213_tpu.bench import (
    cipher_vector_length_sweep,
    heat_sweep,
    pagerank_avg_edges_sweep,
    sort_thread_sweep,
    spmv_suite_sweep,
    write_csv,
)
from cme213_tpu.config import SimParams


def test_cipher_driver():
    assert run_cipher(make_corpus(1 << 12, seed=1), shift=5, replicate=2)


def test_heat2d_driver(tmp_path):
    p = SimParams(nx=40, ny=40, order=8, iters=5)
    res = run_single(p, check_cpu=True, save_files=True, out_dir=str(tmp_path))
    assert res.ok
    assert (tmp_path / "grid_init.txt").exists()
    assert (tmp_path / "grid_final_gpu_global.txt").exists()
    assert (tmp_path / "grid_final_gpu_shared.txt").exists()


def test_heat2d_distributed_driver(tmp_path):
    from cme213_tpu.config import GridMethod

    p = SimParams(nx=16, ny=16, order=2, iters=3,
                  grid_method=GridMethod.BLOCKS_2D, synchronous=False)
    out = run_distributed(p, num_devices=4, save_files=True,
                          out_dir=str(tmp_path))
    assert np.isfinite(out).all()
    assert (tmp_path / "grid_final.txt").exists()


def test_sorts_driver():
    assert run_merge_sort(50_000)
    assert run_radix_sort(50_000, tpu=True)


def test_cipher_sweep_csv(tmp_path):
    rows = cipher_vector_length_sweep(steps=2, max_bytes=1 << 16)
    assert len(rows) == 2 and "uint2_gbs" in rows[0]
    f = tmp_path / "c.csv"
    write_csv(rows, str(f))
    assert f.read_text().count("\n") == 3


def test_pagerank_sweep():
    rows = pagerank_avg_edges_sweep(num_nodes=2048, edges_range=range(2, 4),
                                    iterations=4)
    assert [r["avg_edges"] for r in rows] == [2, 3]
    assert all(r["gbs"] > 0 for r in rows)


def test_heat_sweep():
    rows = heat_sweep(sizes=(32,), orders=(2,), iters=3)
    assert {r["kernel"] for r in rows} == {"xla", "pallas"}


def test_sort_thread_sweep():
    rows = sort_thread_sweep(num_elements=20_000, threads=(1, 2))
    assert len(rows) == 2


def test_spmv_suite_sweep():
    rows = spmv_suite_sweep(names=["jonheart", "dense2"], scale=0.01)
    assert len(rows) == 2
    assert all(float(r["rel_l2"]) < 1e-3 for r in rows)
