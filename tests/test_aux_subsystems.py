"""Aux subsystems: checkpoint/resume, tracing, MatrixMarket reader,
Vigenère CLI table printers."""

import numpy as np
import pytest

from cme213_tpu.core.checkpoint import (
    load_checkpoint,
    run_with_checkpoints,
    save_checkpoint,
)


def test_checkpoint_roundtrip(tmp_path):
    p = str(tmp_path / "ck.npz")
    save_checkpoint(p, 7, state=np.arange(10.0), extra=np.ones(3))
    step, arrays = load_checkpoint(p)
    assert step == 7
    np.testing.assert_array_equal(arrays["state"], np.arange(10.0))
    np.testing.assert_array_equal(arrays["extra"], np.ones(3))
    assert load_checkpoint(str(tmp_path / "missing.npz")) is None


def test_run_with_checkpoints_resume(tmp_path):
    p = str(tmp_path / "run.npz")
    calls = []

    def step(state, k):
        calls.append(k)
        return state + k

    out = run_with_checkpoints(step, np.zeros(4), 10, p, every=3)
    np.testing.assert_array_equal(out, np.full(4, 10.0))
    assert calls == [3, 3, 3, 1]

    # resume: pretend the job died and restart — no extra iterations run
    calls.clear()
    out2 = run_with_checkpoints(step, np.zeros(4), 10, p, every=3)
    np.testing.assert_array_equal(out2, np.full(4, 10.0))
    assert calls == []


def test_matrix_market_reader(tmp_path):
    from cme213_tpu.apps.matrix_market import problem_from_mtx, read_matrix_market

    mtx = tmp_path / "t.mtx"
    mtx.write_text(
        "%%MatrixMarket matrix coordinate real general\n"
        "% comment\n"
        "3 3 4\n"
        "1 1 2.0\n"
        "2 2 3.0\n"
        "3 1 -1.0\n"
        "3 3 4.0\n"
    )
    rows, cols, vals, shape = read_matrix_market(str(mtx))
    assert shape == (3, 3)
    np.testing.assert_array_equal(rows, [0, 1, 2, 2])
    np.testing.assert_array_equal(vals, [2.0, 3.0, -1.0, 4.0])

    prob = problem_from_mtx(str(mtx), iters=4, seed=0)
    assert prob.n == 4 and prob.iters == 4


def test_gr_30_30_real_matrix_end_to_end():
    """VERDICT r3 item 5: a real published SuiteSparse problem through the
    reader → engine → f64 external checker.  examples/gr_30_30.mtx is the
    shipped HB/gr_30_30 reconstruction (pattern exactly the published
    nine-point-star instance; see matrix_market.gr_30_30_mtx)."""
    import os

    from cme213_tpu.apps import spmv_scan as sp
    from cme213_tpu.apps.matrix_market import (gr_30_30_mtx, gr_30_30_path,
                                               problem_from_mtx,
                                               read_matrix_market)

    path = gr_30_30_path()
    assert os.path.exists(path), "shipped real-matrix instance missing"
    # the shipped file must be the generator's output (pattern is forced
    # by the discretization, so this is stable across library versions)
    with open(path) as f:
        assert f.read() == gr_30_30_mtx()
    rows, cols, vals, shape = read_matrix_market(path)
    assert shape == (900, 900) and len(vals) == 7744  # published nnz

    prob = problem_from_mtx(path, iters=50, seed=0)
    out = sp.run_spmv_scan(prob)
    errs = sp.external_check(prob, out)
    assert errs["rel_l2"] < 1e-4, errs


def test_dense2_reconstruction():
    """VERDICT r4 item 5: the dense 2000×2000 suite instance is fully
    pattern-determined — the reconstruction must carry exactly the
    published shape (4,000,000 stored entries over 2000 rows) through the
    readMM construction, and its engine output must pass the f64 check."""
    import numpy as np

    from cme213_tpu.apps import spmv_scan as sp
    from cme213_tpu.apps.matrix_market import dense2_problem

    prob = dense2_problem(iters=2, seed=0)
    assert prob.n == 2000 * 2000
    assert prob.q == 2000
    assert np.all(prob.a == 1.0)
    # run only a couple of iterations: the value here is that the real
    # 4M-element instance goes end-to-end, not the timing
    out = sp.run_spmv_scan(prob)
    errs = sp.external_check(prob, out)
    assert errs["rel_l2"] < 1e-4, errs


def test_real_instance_specs_registry():
    """Both reconstructions ride the suite sweep: names, source labels,
    and working factories."""
    from cme213_tpu.apps.matrix_market import real_instance_specs

    specs = real_instance_specs()
    by_name = {name: (source, factory) for name, source, factory in specs}
    assert set(by_name) == {"gr_30_30", "dense2"}
    for name, (source, factory) in by_name.items():
        assert source.startswith("real ("), (name, source)
        assert "reconstructed" in source


def test_matrix_market_symmetric(tmp_path):
    from cme213_tpu.apps.matrix_market import read_matrix_market

    mtx = tmp_path / "s.mtx"
    mtx.write_text(
        "%%MatrixMarket matrix coordinate real symmetric\n"
        "2 2 2\n"
        "1 1 5.0\n"
        "2 1 7.0\n"
    )
    rows, cols, vals, _ = read_matrix_market(str(mtx))
    # off-diagonal expanded
    assert len(rows) == 3
    assert (7.0 == vals).sum() == 2


def test_overlap_trace_script_end_to_end(tmp_path):
    """The P11 profile-evidence capture stage, driven at toy size on the
    8-device CPU mesh: wall-clock rows for sync/async/CA plus a real
    XPlane file, and the CSV written only after the trace landed (a drop
    mid-trace must leave no CSV, so the capture retries the whole step)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    out = tmp_path / "cap"
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "tpu_overlap_trace.py"),
         str(out), "--size=64", "--order=2", "--iters=8"],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    csv_path = out / "overlap_sync_vs_async.csv"
    assert csv_path.exists()
    content = csv_path.read_text()
    for scheme in ("sync", "async", "ca-k4"):
        assert scheme in content, content
    xplanes = [f for r, _, fs in os.walk(out / "xplane_overlap")
               for f in fs if f.endswith(".xplane.pb")]
    assert xplanes, "no XPlane file written"


def test_vigenere_table_printers(capsys):
    import jax.numpy as jnp

    from cme213_tpu.apps.vigenere import print_digraph_table, print_letter_frequencies

    text = jnp.asarray(np.frombuffer(b"abababab", dtype=np.uint8))
    print_letter_frequencies(text)
    print_digraph_table(text)
    out = capsys.readouterr().out
    assert "Text length: 8" in out
    assert "a: 0.5" in out
    assert "ab:" in out


def test_device_trace(tmp_path):
    import jax.numpy as jnp

    from cme213_tpu.core.trace import device_trace

    with device_trace(str(tmp_path)):
        (jnp.ones(64) * 2).block_until_ready()
    # trace directory created with some content
    assert any(tmp_path.rglob("*"))


def test_pipeline_tune_sweep_quick():
    from cme213_tpu.bench.sweeps import pipeline_tune_sweep

    rows = pipeline_tune_sweep(size=64, order=8, iters=4, ks=(1, 2),
                               targets=(16,))
    # k x {1-D, column-tiled} cells, every one timed without error
    assert {r["kernel"] for r in rows} == {"pipeline-k1", "pipeline2d-k1",
                                           "pipeline-k2", "pipeline2d-k2"}
    assert all(r["error"] == "" and r["ms"] > 0 for r in rows)


def test_heat_kernel_sweep_quick():
    from cme213_tpu.bench.sweeps import heat_kernel_sweep

    rows = heat_kernel_sweep(size=32, order=2, iters=4, ks=(2, 4), tile=8)
    names = [r["kernel"] for r in rows]
    assert names == ["xla", "xla-roll", "xla-conv", "pallas-roll",
                     "xla-roll-k2", "xla-roll-k4",
                     "pipeline-k1", "pipeline2d-k1", "pipeline-k2",
                     "pipeline2d-k2", "pipeline-k4", "pipeline2d-k4",
                     "pallas-k2", "pallas-k4"]
    assert all(r["error"] == "" and r["ms"] > 0 for r in rows)


def test_compile_cache_gating():
    """The persistent compile cache engages for TPU-path processes and
    stays out of explicit-CPU ones (tests, workers, rehearsals)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    snippet = (f"import sys; sys.path.insert(0, {repo!r});"
               "import cme213_tpu, jax;"
               "print('DIR=', jax.config.jax_compilation_cache_dir)")
    # explicit-CPU process: gate must keep the cache off
    out = subprocess.run(
        [sys.executable, "-c", snippet],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=120)
    assert "DIR= None" in out.stdout, out.stderr
    # TPU-path process (no platform override): cache dir configured;
    # reading jax.config does not create a device client, so this is
    # safe even while a capture owns the chip
    env = {**os.environ, "CME213_COMPILE_CACHE": "/tmp/cc_t"}
    env.pop("JAX_PLATFORMS", None)
    out = subprocess.run([sys.executable, "-c", snippet], env=env,
                         capture_output=True, text=True, timeout=120)
    assert "DIR= /tmp/cc_t" in out.stdout, out.stderr


def test_force_cpu_devices_disables_cache():
    """In-process: conftest's force_cpu_devices must have reset the
    cache dir so CPU test compiles don't churn the TPU cache."""
    import jax

    assert jax.config.jax_compilation_cache_dir is None
