"""Test harness: run everything on a fake 8-device CPU mesh.

This preserves the reference's distributed-testing methodology — "compare an
N-rank result against a 1-rank result" (hw5 handout §5.1, SURVEY §4.4/§4.8) —
without cluster hardware, exactly as SURVEY §4.8 prescribes: a CPU platform
with ``--xla_force_host_platform_device_count=8``.

Note: the environment's TPU plugin re-forces its own platform list via
``jax.config.update`` at interpreter startup (sitecustomize), so setting the
``JAX_PLATFORMS`` env var is NOT enough — we must update the config *after*
importing jax.  The XLA_FLAGS env var must still be set *before* the CPU
client is created.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
