# Shared definitions for the device-capture scripts (sourced by
# tpu_capture.sh and tpu_autocapture.sh) — one home for the sweep list,
# the device-failure signatures, and the bench-result gate.

# stderr signatures of a dead/dropped tunnel (vs a sticky kernel/compile
# bug): such failures are retried on the next capture attempt
DEVICE_ERR='UNAVAILABLE|unreachable|DEADLINE|preflight|device hang|device error'

SWEEPS="transfer_bandwidth data_bandwidth_vector_length \
bandwidth_vs_avg_edges scan_bandwidth spmv_suite \
dist_heat_scaling heat_bandwidth pallas_tile heat_kernels pipeline_tune"

bench_ok() {  # $1 = bench json path: holds a real (non-zero) number?
  [ -s "$1" ] && grep -q '"unit": "GB/s"' "$1" \
    && ! grep -q 'DEVICE UNAVAILABLE' "$1"
}

bench_complete() {  # $1: bench_ok AND no per-kernel device-failure rows —
  # a window that closed mid-bench leaves rows like "preflight: device
  # unreachable"; such a file is a partial result worth re-running, not
  # final evidence
  bench_ok "$1" && ! grep -qE "$DEVICE_ERR" "$1"
}

sweep_attempted() {  # $1 = outdir, $2 = sweep: captured, or sticky-failed?
  [ -s "$1/$2.csv" ] && return 0
  [ -s "$1/$2.failed" ] && ! grep -qE "$DEVICE_ERR" "$1/$2.failed"
}
