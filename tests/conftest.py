"""Test harness: run everything on a fake 8-device CPU mesh.

This preserves the reference's distributed-testing methodology — "compare an
N-rank result against a 1-rank result" (hw5 handout §5.1, SURVEY §4.4/§4.8) —
without cluster hardware, exactly as SURVEY §4.8 prescribes: a CPU platform
with ``--xla_force_host_platform_device_count=8``.  The order-sensitive
platform-forcing recipe lives in ``cme213_tpu.core.platform``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cme213_tpu.core.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(8)
