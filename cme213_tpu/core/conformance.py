"""Kernel conformance gating — probe-before-serve wrong-answer detection.

The resilience ladder (``core/resilience.with_fallback``) demotes a rung
that *raises* or goes non-finite, but a kernel can fail worse than that:
it can return a wrong-but-finite grid that every downstream guard happily
serves.  The reference's defense was its dual-implementation methodology —
every kernel diffed against a golden before results were trusted
(``hw2``'s ``grid_final_*`` comparisons, the hw_final external checker) —
applied *offline*, once, by a human.  This module is that check moved
into the serving path, made cheap enough to leave on:

- On the **first use** of a non-reference rung (per process × op × shape
  class), :func:`check` runs a small canonical probe problem through the
  candidate rung and through the op's reference rung (``flat`` scan,
  ``xla`` stencil), and compares — bitwise by default, or to the rung's
  declared tolerance (``max_ulps`` / ``rel_l2``) for kernels whose
  accumulation order legitimately differs.
- A diverging rung records a ``conformance-failed`` event and is demoted
  by the caller exactly like a rung that raised (``FailureKind.
  WRONG_ANSWER``); a matching rung is served.
- Verdicts are **cached** in-process (steady state: one dict lookup) and
  optionally on disk (``CME213_CONFORMANCE_CACHE=<json path>``) so long-
  lived fleets pay the probe once per binary, not once per process.

The probe is sampling, not proof: a rung that matches on the probe can
still diverge on some other shape — the shape class (dtype, stencil
order, temporal-blocking factor, ...) is chosen so the known divergence
axes are probed separately.  ``wrong:<op>`` fault clauses
(``core/faults.py``) perturb a probe output deterministically, so the
whole gate is testable on CPU.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass

import numpy as np

from . import metrics
from .trace import record_event

#: optional on-disk verdict cache (JSON) shared across processes
CACHE_ENV = "CME213_CONFORMANCE_CACHE"


@dataclass(frozen=True)
class Verdict:
    """Outcome of one conformance probe (or its cached replay)."""

    ok: bool
    detail: str          # "bitwise" / "rel_l2=1.2e-07<=1e-05" / mismatch
    cached: bool = False


# (op, rung, shape_class) -> Verdict — the steady-state dict lookup
_VERDICTS: dict[tuple[str, str, str], Verdict] = {}
_DISK_LOADED = False


def reset() -> None:
    """Forget every cached verdict (tests); the disk cache is re-read."""
    global _DISK_LOADED
    _VERDICTS.clear()
    _DISK_LOADED = False


def _cache_key(op: str, rung: str, shape_class: str) -> str:
    return f"{op}|{rung}|{shape_class}"


def _load_disk_cache() -> None:
    """Merge persisted verdicts (non-destructively: in-process wins)."""
    global _DISK_LOADED
    _DISK_LOADED = True
    path = os.environ.get(CACHE_ENV)
    if not path or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return  # a corrupt cache must never block serving; probes re-run
    for key, v in data.items():
        parts = key.split("|")
        if len(parts) != 3 or not isinstance(v, dict) or "ok" not in v:
            continue
        tup = (parts[0], parts[1], parts[2])
        _VERDICTS.setdefault(tup, Verdict(
            ok=bool(v["ok"]), detail=str(v.get("detail", "disk-cache")),
            cached=True))


def _persist(op: str, rung: str, shape_class: str, verdict: Verdict) -> None:
    path = os.environ.get(CACHE_ENV)
    if not path:
        return
    try:
        data = {}
        if os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except (OSError, ValueError):
        data = {}
    data[_cache_key(op, rung, shape_class)] = {
        "ok": verdict.ok, "detail": verdict.detail}
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(data, f, indent=0, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass  # a read-only cache dir must never block serving


def _compare(out, ref, rel_l2: float, max_ulps: int) -> tuple[bool, str]:
    """(ok, detail) for candidate vs reference probe outputs."""
    out = np.asarray(out)
    ref = np.asarray(ref)
    if out.shape != ref.shape or out.dtype != ref.dtype:
        return False, (f"shape/dtype mismatch: {out.dtype}{out.shape} vs "
                       f"{ref.dtype}{ref.shape}")
    if not np.isfinite(out).all():
        return False, "non-finite candidate output"
    if max_ulps:
        from .compare import ulp_distance

        d = int(np.max(ulp_distance(ref, out))) if out.size else 0
        return d <= max_ulps, f"ulps={d} (tol {max_ulps})"
    if rel_l2:
        denom = float(np.linalg.norm(ref.astype(np.float64)))
        err = (float(np.linalg.norm((out - ref).astype(np.float64)))
               / max(denom, np.finfo(np.float64).tiny))
        return err <= rel_l2, f"rel_l2={err:.3e} (tol {rel_l2:g})"
    n_bad = int(np.count_nonzero(out != ref))
    return n_bad == 0, ("bitwise" if n_bad == 0
                        else f"bitwise mismatch ({n_bad}/{out.size} elems)")


def check(op: str, rung: str, shape_class: str, candidate, reference,
          rel_l2: float = 0.0, max_ulps: int = 0) -> Verdict:
    """Probe ``rung`` against the op's reference rung; cached per
    (op, rung, shape_class).

    ``candidate``/``reference`` are zero-arg callables returning the probe
    outputs (arrays); they run only on a cache miss.  The comparison is
    bitwise unless the rung declares a tolerance (``max_ulps`` wins over
    ``rel_l2``).  The candidate output passes through ``faults.
    maybe_perturb(op, ...)`` so ``wrong:<op>`` clauses can poison exactly
    one probe.  Divergence records a ``conformance-failed`` event; every
    actual probe records ``conformance-probe``.
    """
    if not _DISK_LOADED:
        _load_disk_cache()
    key = (op, rung, shape_class)
    hit = _VERDICTS.get(key)
    if hit is not None:
        metrics.counter("conformance.cache_hits").inc()
        return Verdict(hit.ok, hit.detail, cached=True)

    from .faults import maybe_fail_stage, maybe_perturb

    # staged forensics: a `stage:<op>.<rung>:conformance` clause kills the
    # probe here, pre-tagged, so gate-path attribution is injectable
    maybe_fail_stage(f"{op}.{rung}", "conformance")
    start = time.perf_counter()
    out = maybe_perturb(op, candidate())
    ref = reference()
    ok, detail = _compare(out, ref, rel_l2, max_ulps)
    ms = round((time.perf_counter() - start) * 1e3, 3)
    verdict = Verdict(ok, detail)
    _VERDICTS[key] = verdict
    metrics.counter("conformance.probes").inc()
    record_event("conformance-probe", op=op, rung=rung,
                 shape_class=shape_class, ok=ok, ms=ms)
    if not ok:
        metrics.counter("conformance.failed").inc()
        record_event("conformance-failed", op=op, rung=rung,
                     shape_class=shape_class, detail=detail)
    _persist(op, rung, shape_class, verdict)
    return verdict


def verdicts() -> dict:
    """Snapshot of cached verdicts (introspection/tests)."""
    return {_cache_key(*k): v for k, v in _VERDICTS.items()}
