import sys
from pathlib import Path

import jax
import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as ge  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == args[0].shape
    assert np.isfinite(np.asarray(out)).all()


def test_dryrun_multichip_8():
    ge.dryrun_multichip(8)


def test_dryrun_multichip_nonsquare():
    ge.dryrun_multichip(2)
