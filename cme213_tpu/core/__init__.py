from .timing import PhaseTimer, bandwidth_gbs, gflops
from .compare import ulp_distance, almost_equal_ulps
from .errors import check_op, FrameworkError

__all__ = [
    "PhaseTimer",
    "bandwidth_gbs",
    "gflops",
    "ulp_distance",
    "almost_equal_ulps",
    "check_op",
    "FrameworkError",
]
