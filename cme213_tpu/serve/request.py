"""Request/result types for the serving front end.

A request names a workload op (``spmv_scan`` / ``heat`` / ``cipher``),
carries an op-specific payload, and optionally a relative deadline.  A
result is either served (``ok``), refused with a structured reason
(``shed`` — the 429 analog: the caller can retry, back off, or route
elsewhere, instead of hanging on unbounded latency), or failed (every
rung of the op's ladder raised).  Shed reasons:

- ``queue-full``  — bounded-queue backpressure: the queue was at
  capacity when the request arrived;
- ``deadline``    — the request could not *start* before its deadline
  (rejected before execution — never executed late and discarded);
- ``admission``   — even a single-request program for this shape class
  exceeds the memory budget (``core/admission.py``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

#: result statuses
OK = "ok"
SHED = "shed"
FAILED = "failed"

#: shed reasons (the ``serve.shed.<reason>`` counter suffixes)
QUEUE_FULL = "queue-full"
DEADLINE = "deadline"
ADMISSION = "admission"


#: lifecycle phase names, in stamp order (the ``timing`` dict keys are
#: ``<phase>_ms`` plus ``total_ms``)
PHASES = ("queue", "admit", "batch_wait", "run")


@dataclass
class SolveRequest:
    rid: int                      # server-assigned, unique per server
    op: str                       # workload adapter name
    payload: object               # op-specific problem description
    submitted_s: float            # server-clock time of acceptance
    deadline_s: float | None = None   # absolute server-clock deadline
    tenant: str = "default"       # billing/attribution principal
    # lifecycle phase stamps, all on the server clock (monotonic within a
    # request by construction: stamped in submit/step/execute order)
    dequeued_s: float | None = None   # pulled into a candidate batch
    admitted_s: float | None = None   # cleared the admission preflight
    executed_s: float | None = None   # handed to the kernel ladder
    completed_s: float | None = None  # ladder returned
    # process-spanning trace id (core/trace): stamped at submit, carried
    # through queue -> batch -> execution -> result, so one id follows
    # the request across the loadgen/server process boundary
    trace_id: str | None = None
    # wire-carried span context: the upstream hop span id (client root
    # or front-tier route hop) this request's replica-side hops parent
    # under, so the merged trace renders as one cross-process tree
    parent_span_id: str | None = None
    # open request-hop spans (core.trace.OpenSpan), server-managed:
    # ``hop`` covers submit -> completion, ``run_hop`` execute ->
    # completion; both end with the result (or the shed/fail path)
    hop: object = None
    run_hop: object = None

    def timing(self) -> dict:
        """Phase breakdown in ms (``queue``/``admit``/``batch_wait``/
        ``run`` + ``total``); phases not reached are None.  Sums of the
        reached phases equal ``total_ms`` up to rounding — every stamp
        comes from the same clock."""
        def ms(a, b):
            return None if (a is None or b is None) else round((b - a) * 1e3, 3)
        return {
            "queue_ms": ms(self.submitted_s, self.dequeued_s),
            "admit_ms": ms(self.dequeued_s, self.admitted_s),
            "batch_wait_ms": ms(self.admitted_s, self.executed_s),
            "run_ms": ms(self.executed_s, self.completed_s),
            "total_ms": ms(self.submitted_s, self.completed_s),
        }


@dataclass
class SolveResult:
    rid: int
    op: str
    status: str                   # OK | SHED | FAILED
    reason: str | None = None     # shed reason / failure summary
    value: object = None          # op-specific result (OK only)
    rung: str | None = None       # kernel rung that served (OK only)
    shape_class: str | None = None
    latency_ms: float | None = None   # submit -> completion (server clock)
    batch_size: int | None = None     # lanes in the serving program
    degraded: bool = False            # served under degraded mode
    tenant: str = "default"           # principal the request ran under
    timing: dict | None = None        # phase breakdown (SolveRequest.timing)
    trace_id: str | None = None       # trace the request belonged to

    @property
    def ok(self) -> bool:
        return self.status == OK


@dataclass
class RequestSpec:
    """A loadgen-side request description: what to submit, before the
    server assigns it an id."""

    op: str
    payload: object
    deadline_ms: float | None = None
    tags: dict = field(default_factory=dict)
    tenant: str = "default"
