"""Workload adapters: how each solver batches, buckets, and degrades.

The request population is the paper's hw workload mix — heat grids
(hw2/hw5), SpMV-scan problems (hw_final), shift-cipher cracks (hw1) —
and each adapter maps its payload type onto the serving layer's four
needs:

- **shape-class keying** (``shape_class``): requests whose jitted
  program would be identical share a bucket, using the same keys the
  conformance cache uses (``core/conformance.py``) — spmv by
  ``n/iters``, heat by padded grid shape/order/iters, cipher by byte
  length.  ``coarse=True`` is the degraded-mode keying: spmv rounds
  ``n`` up to the next power of two (requests are zero-padded with a
  quarantined tail segment — ``apps.spmv_scan.pad_problem`` — so
  near-sized classes merge into one program and the compile-cache stops
  fragmenting under pressure); heat and cipher classes are exact by
  construction (padding a grid would move its physical boundary).
- **batched execution** (``run_batch``): all payloads of one bucket run
  as ONE device program via the apps' vmap/stacking entry points, each
  lane bitwise-equal to its serial solve.
- **rung ladders** (``rungs``): the kernel candidates ``with_fallback``
  walks, per mode.  Degraded mode serves from the always-conformant
  reference rung only (no probes, no extra compile classes — predictable
  over peak-fast).
- **admission preflight** (``preflight_builder``): a ``size ->
  Decision`` closure over the batched program, for
  ``core/admission.admit_batch`` when a memory budget is set.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


@dataclass
class CipherRequest:
    """A shift-cipher solve: encrypt/decrypt ``text`` by ``shift``."""

    text: np.ndarray        # (n,) uint8
    shift: int


class SpmvAdapter:
    """``apps.spmv_scan.Problem`` payloads; XLA scan rungs only (the
    Pallas rungs don't stack — interpret mode on CPU would dominate any
    batching win, and serving wants predictable latency)."""

    op = "spmv_scan"

    def shape_class(self, prob, coarse: bool = False) -> str:
        n = _next_pow2(prob.n) if coarse else prob.n
        return f"n{n}/i{prob.iters}"

    def rungs(self, degraded: bool = False) -> tuple[str, ...]:
        # blocked is the O(n) throughput rung; flat is the bitwise-stable
        # reference every other rung is conformance-checked against, so
        # degraded mode serves from it alone
        return ("flat",) if degraded else ("blocked", "flat")

    def run_batch(self, probs, rung: str, coarse: bool = False):
        from ..apps.spmv_scan import pad_problem, run_spmv_scan_batched

        if coarse:
            n_to = _next_pow2(max(p.n for p in probs))
            padded = [pad_problem(p, n_to) for p in probs]
            outs = run_spmv_scan_batched(padded, kernel=rung)
            return [o[:p.n] for p, o in zip(probs, outs)]
        return run_spmv_scan_batched(list(probs), kernel=rung)

    def preflight_builder(self, probs, rung: str, coarse: bool = False):
        from ..core import admission
        from ..apps.spmv_scan import _iterate_batched, pad_problem

        import jax.numpy as jnp

        p0 = probs[0] if not coarse else pad_problem(
            probs[0], _next_pow2(max(p.n for p in probs)))
        n, iters = p0.n, p0.iters

        def preflight_at(size: int) -> admission.Decision:
            z = jnp.zeros((size, n), jnp.float32)
            fl = jnp.zeros((size, n), jnp.int32)
            return admission.preflight(
                _iterate_batched, z, z, fl, op=f"serve.{self.op}",
                iters=iters, scan=rung)

        return preflight_at


class HeatAdapter:
    """``config.SimParams`` payloads — the initial grid is derived from
    the params the way the reference's driver built it, and CFL factors
    ride as vmapped per-lane scalars (so requests need not share
    diffusivity to share a bucket)."""

    op = "heat"

    def shape_class(self, params, coarse: bool = False) -> str:
        return f"{params.gy}x{params.gx}/order{params.order}/i{params.iters}"

    def rungs(self, degraded: bool = False) -> tuple[str, ...]:
        # one conformant rung: the XLA stencil (the Pallas pipeline runs
        # interpreted off-TPU — never the serving choice there, and
        # batching it is ROADMAP work, not this layer's)
        return ("xla",)

    def run_batch(self, params_list, rung: str, coarse: bool = False):
        from ..apps.heat2d import run_heat_batched
        from ..grid import make_initial_grid

        if rung != "xla":
            raise ValueError(f"unknown heat rung {rung!r}")
        p0 = params_list[0]
        grids = [np.asarray(make_initial_grid(p)) for p in params_list]
        return run_heat_batched(grids, p0.iters, p0.order,
                                [p.xcfl for p in params_list],
                                [p.ycfl for p in params_list])

    def preflight_builder(self, params_list, rung: str,
                          coarse: bool = False):
        from ..core import admission
        from ..apps.heat2d import _heat_batched

        import jax.numpy as jnp

        p0 = params_list[0]

        def preflight_at(size: int) -> admission.Decision:
            z = jnp.zeros((size, p0.gy, p0.gx), jnp.float32)
            c = jnp.zeros((size,), jnp.float32)
            return admission.preflight(
                _heat_batched, z, p0.iters, p0.order, c, c,
                op=f"serve.{self.op}")

        return preflight_at


class CipherAdapter:
    """:class:`CipherRequest` payloads.  Two bitwise-identical rungs —
    ``packed`` (4-bytes-per-lane, the reference's uint kernel) and
    ``bytes`` (plain per-byte) — which is what makes this op the breaker
    demonstration: a ``fail:serve.cipher.packed``-injected rung opens its
    circuit and the ``bytes`` rung serves bitwise-equal results."""

    op = "cipher"

    def shape_class(self, req: CipherRequest, coarse: bool = False) -> str:
        return f"n{req.text.shape[0]}/u8"

    def rungs(self, degraded: bool = False) -> tuple[str, ...]:
        return ("packed", "bytes")

    def run_batch(self, reqs, rung: str, coarse: bool = False):
        import jax.numpy as jnp

        from ..ops.elementwise import (
            shift_cipher_batched,
            shift_cipher_packed_batched,
        )

        data = jnp.asarray(np.stack([r.text for r in reqs]))
        shifts = jnp.asarray(np.array([r.shift for r in reqs],
                                      dtype=np.int32))
        if rung == "packed":
            out = shift_cipher_packed_batched(data, shifts)
        elif rung == "bytes":
            out = shift_cipher_batched(data, shifts)
        else:
            raise ValueError(f"unknown cipher rung {rung!r}")
        out = np.asarray(out)
        return [out[i] for i in range(len(reqs))]

    def preflight_builder(self, reqs, rung: str, coarse: bool = False):
        return None  # bytes in ≈ bytes out: admission adds nothing here


#: the default adapter registry — the hw workload mix as request types
ADAPTERS = {a.op: a for a in (SpmvAdapter(), HeatAdapter(), CipherAdapter())}
