"""Deterministic English-like corpus generator (reference hw1/hw3 data).

The reference ships a 1.2 MB public-domain novel as the workload input for
the shift-cipher and Vigenère units (``hw/hw1/programming/mobydick.txt``;
hw3 reuses it plus a wiki dump).  This environment has no network, and
copying the reference's data files is off the table — so the framework
ships a *generator* instead: Zipf-weighted sampling over a vocabulary of
real English words, with sentence/paragraph structure.

Because the emitted words are real English spellings drawn with realistic
rank frequencies, the statistics the hw3 attack depends on come out right
without any tuning:

- unigram letter frequencies land in English order (e, t, a, o, ...) —
  what the per-coset frequency attack needs (``solve_cipher.cu:214-248``);
- the index of coincidence of the sanitized stream is ~1.7 (English), far
  from 1.0 (uniform) — what the key-length detector needs
  (``solve_cipher.cu:187-208``);
- the top digraphs are the English ones (th, he, in, er, an) — what the
  digraph table displays (``solve_cipher.cu:156-180``).

``python -m cme213_tpu.apps.corpus out.txt [n_bytes] [seed]`` writes the
corpus; the repo ships the canonical 1.25 MB instance at
``examples/corpus.txt`` so tests and benches don't depend on RNG-stream
stability across numpy versions.
"""

from __future__ import annotations

import numpy as np

# Vocabulary: ~320 common English words (function words first — in real
# English text the top ~100 words cover roughly half of all tokens, which
# is what drags the letter distribution to its familiar shape).  Sampled
# with Zipf weights 1/(rank + 2.7) so "the"/"of"/"and" dominate the way
# they do in running text.
_VOCAB = """
the of and a to in is was he that it his her you as had with for she on at
by which have or from this him but not they all were are we when your can
said there use an each do how their if will up other about out many then
them these so some would make like into time has look two more write go see
number no way could people my than first water been call who oil its now
find long down day did get come made may part over new sound take only
little work know place year live me back give most very after thing our
just name good sentence man think say great where help through much before
line right too mean old any same tell boy follow came want show also around
form three small set put end does another well large must big even such
because turn here why ask went men read need land different home us move
try kind hand picture again change off play spell air away animal house
point page letter mother answer found study still learn should world high
every near add food between own below country plant last school father keep
tree never start city earth eye light thought head under story saw left
night kept white children begin got walk example ease paper group always
music those both mark often until mile river car feet care second book
carry took science eat room friend began idea fish mountain stop once base
hear horse cut sure watch color face wood main open seem together next
while sea along might close something morning captain whale ship ocean
wind against pattern slow center love person money serve appear road map
rain rule govern pull cold notice voice unit power town fine certain fly
fall lead cry dark machine note wait plan figure star box noun field rest
correct able pound done beauty drive stood contain front teach week final
gave green quick develop sleep warm free minute strong special mind behind
clear tail produce fact street inch multiply nothing course stay wheel
full force blue object decide surface deep moon island foot system busy
test record boat common gold possible plane stead dry wonder laugh
thousand ago ran check game shape equate hot miss brought heat snow tire
bring yes distant fill east paint language among
""".split()

_ZIPF = 1.0 / (np.arange(len(_VOCAB)) + 2.7)
_ZIPF = _ZIPF / _ZIPF.sum()

# numpy version the shipped examples/corpus.txt was generated with: the
# Generator bit-stream is only guaranteed stable within a version, so the
# byte-equality drift test gates on it (statistics tests always run)
GENERATED_WITH_NUMPY = "2.0.2"


def make_english_corpus(n_bytes: int = 1_250_000, seed: int = 0,
                        line_width: int = 72) -> bytes:
    """Deterministic English-like text of (at least) ``n_bytes`` bytes.

    Sentences of 5–17 Zipf-sampled words, capitalized, comma roughly every
    8 words, period at the end; paragraphs of 3–7 sentences separated by a
    blank line; lines wrapped at ``line_width`` like a plain-text novel.
    """
    rng = np.random.default_rng(seed)
    # Draw word indices in bulk blocks; a block that runs dry mid-corpus
    # is extended from the same stream, so the output length can never
    # fall short of n_bytes whatever the sentence-length draws do.
    block = max(int(n_bytes / 4.5) + 64, 256)
    words = rng.choice(len(_VOCAB), size=block, p=_ZIPF)
    i = 0

    def next_words(k: int) -> np.ndarray:
        nonlocal words, i
        if i + k > words.size:
            words = np.concatenate(
                [words[i:], rng.choice(len(_VOCAB), size=block, p=_ZIPF)])
            i = 0
        w = words[i:i + k]
        i += k
        return w

    out: list[str] = []
    size = 1  # the trailing newline; kept exact so the >= n_bytes
    # guarantee holds even when the loop exits right at the boundary
    while size < n_bytes:
        para_sents = int(rng.integers(3, 8))
        para: list[str] = []
        for _ in range(para_sents):
            sent_len = int(rng.integers(5, 18))
            toks = [_VOCAB[w] for w in next_words(sent_len)]
            toks[0] = toks[0].capitalize()
            # a comma mid-sentence, where real prose would pause
            if sent_len >= 9:
                cut = int(rng.integers(3, sent_len - 2))
                toks[cut] = toks[cut] + ","
            para.append(" ".join(toks) + ".")
        text = _wrap(" ".join(para), line_width)
        out.append(text)
        # "\n\n" separators join paragraphs, so only non-first paragraphs
        # carry the extra 2 bytes — size tracks the emitted length exactly
        size += len(text) + (2 if len(out) > 1 else 0)
    return ("\n\n".join(out) + "\n").encode("ascii")


def _wrap(text: str, width: int) -> str:
    """Greedy line wrap (textwrap-free: no hyphenation, deterministic)."""
    lines: list[str] = []
    line = ""
    for tok in text.split(" "):
        if line and len(line) + 1 + len(tok) > width:
            lines.append(line)
            line = tok
        else:
            line = f"{line} {tok}" if line else tok
    if line:
        lines.append(line)
    return "\n".join(lines)


def corpus_path() -> str:
    """Path of the shipped canonical corpus (examples/corpus.txt)."""
    import os

    return os.path.join(os.path.dirname(__file__), "..", "..", "examples",
                        "corpus.txt")


def load_corpus(n_bytes: int | None = None) -> np.ndarray:
    """The shipped corpus as uint8; falls back to generating one in memory.

    With ``n_bytes``, tiles/truncates to exactly that many bytes (the
    cipher sweeps size their inputs this way).
    """
    import os

    path = corpus_path()
    if os.path.exists(path):
        data = np.fromfile(path, dtype=np.uint8)
    else:
        data = np.frombuffer(make_english_corpus(), dtype=np.uint8)
    if n_bytes is not None:
        reps = -(-n_bytes // data.size)
        data = np.tile(data, reps)[:n_bytes]
    return data


def main(argv: list[str]) -> int:
    out = argv[1] if len(argv) > 1 else "corpus.txt"
    n = int(argv[2]) if len(argv) > 2 else 1_250_000
    seed = int(argv[3]) if len(argv) > 3 else 0
    data = make_english_corpus(n, seed)
    with open(out, "wb") as f:
        f.write(data)
    print(f"wrote {out}: {len(data)} bytes")
    return 0


if __name__ == "__main__":
    import sys

    raise SystemExit(main(sys.argv))
