"""Make ``cme213_tpu`` importable from ``python scripts/<tool>.py``.

Running a file inside scripts/ puts scripts/ (not the repo root) at
``sys.path[0]``; importing this module from a sibling script prepends the
repo root so the package resolves without an installed distribution or a
PYTHONPATH export.
"""
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
