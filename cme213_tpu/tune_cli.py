"""``python -m cme213_tpu tune`` — the autotuner front end.

Three subcommands over ``core/tune.py``:

- ``run``    search one or more ops' registered candidate spaces
  (conformance-gate, warm, median-of-k time) and persist the winners to
  the ``CME213_TUNE_CACHE`` JSON cache dispatch consults;
- ``show``   print the cached winners (merged disk + in-process view);
- ``clear``  drop every cached winner, in-process and on disk.

``tune run --op spmv_scan,heat`` is the offline step; afterwards every
``run_spmv_scan`` / ``run_heat_resilient`` / serve batch / auto scan
dispatch in any process pointed at the same cache resolves its statics
as tuned-or-default (``tune-hit`` events in the trace), and
``CME213_TUNE=0`` restores the built-in defaults without touching the
cache.
"""

from __future__ import annotations

import argparse
import json
import sys


def _run_kwargs(op: str, args: argparse.Namespace) -> dict:
    """Per-op kwargs for ``tune.run`` from the shared CLI flags — each
    space builder only receives the knobs it declares."""
    if op.startswith("serve."):
        return {"max_batch": args.max_batch, "seed": args.seed}
    kw: dict = {}
    if op == "spmv_scan":
        kw = {"n": args.n, "iters": args.iters, "dtype": args.dtype}
    elif op == "segmented_scan":
        kw = {"dtype": args.dtype}
        if args.crossover_n is not None:
            kw["n"] = args.crossover_n
    elif op == "heat":
        kw = {"gy": args.gy, "gx": args.gx, "order": args.order,
              "k": args.k, "iters": args.heat_iters, "dtype": args.dtype}
    elif op == "sort":
        kw = {"n": args.n}
    return kw


def _cmd_run(args: argparse.Namespace) -> int:
    from .core import tune

    ops = [o.strip() for o in args.op.split(",") if o.strip()]
    if not ops:
        print("tune run: --op needs at least one op", file=sys.stderr)
        return 2
    reports = []
    for op in ops:
        try:
            rep = tune.run(op, runs=args.runs, persist=not args.dry_run,
                           **_run_kwargs(op, args))
        except tune.TuneError as e:
            print(f"tune run: {e}", file=sys.stderr)
            return 1
        reports.append(rep)
    if args.as_json:
        print(json.dumps(reports, indent=2))
        return 0
    for rep in reports:
        w = rep["winner"]
        print(f"{rep['op']} [{rep['shape_class']}/{rep['dtype']}] on "
              f"{rep['device']}: winner {w['candidate']} "
              f"({w['ms']} ms, {w['gbs']} GB/s)")
        for t in rep["trials"]:
            mark = "*" if t["candidate"] == w["candidate"] else " "
            if t["ok"]:
                print(f"  {mark} {t['candidate']:<24} {t['ms']:>10} ms  "
                      f"{t['gbs']:>8} GB/s")
            else:
                print(f"  {mark} {t['candidate']:<24} "
                      f"REJECTED ({t.get('error', 'gated out')})")
    if args.dry_run:
        print("(dry run: winners NOT persisted)")
    return 0


def _cmd_show(args: argparse.Namespace) -> int:
    from .core import tune

    recs = tune.entries()
    if args.as_json:
        print(json.dumps(recs, indent=2, sort_keys=True))
        return 0
    if not recs:
        where = tune.cache_path() or f"unset — set {tune.CACHE_ENV}"
        print(f"tune: no cached winners (cache file: {where})")
        return 0
    print(f"{len(recs)} cached winner(s)"
          + (f" [{tune.cache_path()}]" if tune.cache_path() else ""))
    for key in sorted(recs):
        rec = recs[key]
        device, op, shape_class, dtype = key.split("|")
        statics = json.dumps(rec["statics"], sort_keys=True)
        print(f"  {device:<8} {op:<16} {shape_class:<20} {dtype:<8} "
              f"-> {rec['candidate']:<20} {statics} "
              f"({rec['ms']} ms, {rec['gbs']} GB/s)")
    return 0


def _cmd_clear(args: argparse.Namespace) -> int:
    from .core import tune

    n = tune.clear()
    print(f"tune: cleared {n} winner(s)")
    return 0


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="tune",
        description="measured autotuning of dispatch statics: search the "
                    "registered per-op candidate spaces and persist the "
                    "winners (CME213_TUNE_CACHE) for dispatch to consume")
    sub = ap.add_subparsers(dest="cmd", required=True)

    runp = sub.add_parser(
        "run", help="gate, time, and persist winners for one or more ops")
    runp.add_argument("--op", default="spmv_scan",
                      help="comma-separated ops: spmv_scan, segmented_scan, "
                           "heat, sort, serve.<mix-op> (e.g. serve.spmv)")
    runp.add_argument("--n", type=int, default=1 << 20,
                      help="problem size for spmv_scan / sort")
    runp.add_argument("--iters", type=int, default=8,
                      help="spmv_scan solve iterations")
    runp.add_argument("--crossover-n", type=int, default=None,
                      help="segmented_scan contested size "
                           "(default: the built-in threshold)")
    runp.add_argument("--gy", type=int, default=64, help="heat grid rows")
    runp.add_argument("--gx", type=int, default=64, help="heat grid cols")
    runp.add_argument("--order", type=int, default=2,
                      help="heat stencil order (2|4|6)")
    runp.add_argument("--k", type=int, default=1,
                      help="heat steps fused per halo exchange")
    runp.add_argument("--heat-iters", type=int, default=4,
                      help="heat timed iterations")
    runp.add_argument("--dtype", default="float32")
    runp.add_argument("--max-batch", type=int, default=8,
                      help="serve.<op> width ceiling")
    runp.add_argument("--seed", type=int, default=0)
    runp.add_argument("--runs", type=int, default=None,
                      help="measured runs per candidate (median taken)")
    runp.add_argument("--dry-run", action="store_true",
                      help="search and report but do not persist winners")
    runp.add_argument("--json", action="store_true", dest="as_json")
    runp.set_defaults(fn=_cmd_run)

    showp = sub.add_parser("show", help="print the cached winners")
    showp.add_argument("--json", action="store_true", dest="as_json")
    showp.set_defaults(fn=_cmd_show)

    clearp = sub.add_parser(
        "clear", help="drop every cached winner (in-process and on disk)")
    clearp.set_defaults(fn=_cmd_clear)

    args = ap.parse_args(argv)
    if getattr(args, "runs", None) is None and hasattr(args, "runs"):
        from .core import tune
        args.runs = tune.TRIAL_RUNS
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
