"""MatrixMarket → SpMV-scan problem instances (the readMM.py parity path).

The reference's dataset generators (``hw/hw_final/programming/aux/readMM.py``,
``aux/fileReadMM.py``) read SuiteSparse ``.mtx`` files with SciPy and emit
``a.txt``/``x.txt`` instances: ``a`` = the nonzero values, ``s`` = a random
sorted subset of indices (with 0/n sentinels), ``k`` = random gather indices,
``x`` = uniform(−1,1), ``N`` ∈ [5,100].  This module does the same with a
dependency-free coordinate-format parser, so real SuiteSparse matrices can be
fed to the engine when available.
"""

from __future__ import annotations

import gzip

import numpy as np

from .spmv_scan import Problem


def read_matrix_market(path: str):
    """Minimal MatrixMarket coordinate parser.

    Supports ``matrix coordinate (real|integer|pattern) (general|symmetric)``.
    Returns (rows, cols, values, shape) with 0-based indices, symmetric
    entries expanded.
    """
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        header = f.readline().strip().lower().split()
        if header[:2] != ["%%matrixmarket", "matrix"]:
            raise ValueError("not a MatrixMarket matrix file")
        if header[2] != "coordinate":
            raise ValueError("only coordinate format supported")
        field, sym = header[3], header[4]
        line = f.readline()
        while line.startswith("%"):
            line = f.readline()
        nr, nc, nnz = (int(v) for v in line.split())
        data = np.loadtxt(f, ndmin=2)
    rows = data[:, 0].astype(np.int64) - 1
    cols = data[:, 1].astype(np.int64) - 1
    if field == "pattern":
        vals = np.ones(rows.shape[0], dtype=np.float32)
    else:
        vals = data[:, 2].astype(np.float32)
    if sym == "symmetric":
        off = rows != cols
        rows, cols = (np.concatenate([rows, cols[off]]),
                      np.concatenate([cols, rows[off]]))
        vals = np.concatenate([vals, vals[off]])
    return rows, cols, vals, (nr, nc)


def gr_30_30_mtx() -> str:
    """Reconstruct SuiteSparse ``HB/gr_30_30`` as MatrixMarket text.

    The published problem is exactly defined: the nine-point star
    discretization of the Laplacian on a 30×30 grid (n = 900,
    nnz = 7744 expanded — 900 diagonal + 6844 king-graph adjacencies),
    symmetric.  This environment has no network access, so the framework
    ships this *reconstruction* instead of the downloaded file: the
    nonzero pattern is forced by the discretization and matches the
    SuiteSparse instance; values use the standard 9-point star
    coefficients (8 on the diagonal, −1 for the eight neighbours).
    Stored as symmetric/lower like the original HB-derived .mtx
    (4322 stored entries), which also exercises the reader's symmetric
    expansion path.
    """
    side = 30
    entries = []  # (row, col, value) 1-based, lower triangle
    for i in range(side):
        for j in range(side):
            r = i * side + j
            entries.append((r + 1, r + 1, 8.0))
            for di in (-1, 0, 1):
                for dj in (-1, 0, 1):
                    if di == 0 and dj == 0:
                        continue
                    ni, nj = i + di, j + dj
                    if not (0 <= ni < side and 0 <= nj < side):
                        continue
                    c = ni * side + nj
                    if c < r:  # store lower triangle only
                        entries.append((r + 1, c + 1, -1.0))
    entries.sort(key=lambda e: (e[1], e[0]))  # column-major like HB files
    n = side * side
    lines = [
        "%%MatrixMarket matrix coordinate real symmetric",
        "% HB/gr_30_30 — nine-point star discretization on a 30x30 grid.",
        "% Reconstructed from the published problem definition (no network",
        "% access in this environment): pattern is exactly the SuiteSparse",
        "% instance's (n=900, nnz=7744 expanded); values are the standard",
        "% 9-point star coefficients.",
        f"{n} {n} {len(entries)}",
    ]
    lines += [f"{r} {c} {v:.1f}" for r, c, v in entries]
    return "\n".join(lines) + "\n"


def gr_30_30_path() -> str:
    """Path of the shipped real-matrix instance (examples/gr_30_30.mtx)."""
    import os

    return os.path.join(os.path.dirname(__file__), "..", "..", "examples",
                        "gr_30_30.mtx")


def problem_from_mtx(path: str, iters: int | None = None,
                     seed: int = 0) -> Problem:
    """readMM.py construction: values → ``a``; random sorted row-index subset
    → ``s``; random ``k``; uniform(−1,1) ``x``; N ∈ [5,100]."""
    rng = np.random.default_rng(seed)
    _, _, vals, (nr, _) = read_matrix_market(path)
    n = vals.shape[0]
    p_interior = min(max(nr - 1, 1), n - 1)
    interior = np.sort(rng.choice(np.arange(1, n), size=p_interior,
                                  replace=False))
    s = np.concatenate([[0], interior, [n]]).astype(np.int32)
    q = max(nr, 2)
    k = rng.integers(0, q, size=n, dtype=np.int32)
    x = rng.uniform(-1, 1, size=q).astype(np.float32)
    if iters is None:
        iters = int(rng.integers(5, 101))
    prob = Problem(vals.astype(np.float32), s, k, x, iters)
    prob.validate()
    return prob
