from .stencil import (STENCIL_COEFFS, BORDER_FOR_ORDER, stencil_interior,
                      stencil_interior_conv, heat_step, run_heat,
                      run_heat_conv)
from .elementwise import (
    shift_cipher,
    shift_cipher_packed,
    vigenere_shift,
    vigenere_unshift,
)
from .scan import inclusive_scan, exclusive_scan, blocked_inclusive_scan
from .segmented import (
    BLOCKED_SCAN_THRESHOLD,
    head_flags_from_starts,
    segment_ids_from_starts,
    segmented_scan,
    segmented_scan_blocked,
    segmented_scan_flat,
    segmented_scan_from_starts,
    validate_segments,
)
from .histogram import histogram_sort, histogram_onehot, histogram_segment
from .sort import sort, sort_pairs, radix_sort, bitonic_sort
from .gather import csr_row_ids, pagerank_propagate, pagerank_iterate
from .spmv import csr_spmv, ell_spmv, csr_to_ell
from .transpose import transpose_pallas, transpose_xla
from .elementwise import saxpy, parallel_sum
from .segmented import segmented_scan_dense

__all__ = [
    "STENCIL_COEFFS",
    "BORDER_FOR_ORDER",
    "stencil_interior",
    "heat_step",
    "run_heat",
    "run_heat_conv",
    "stencil_interior_conv",
    "shift_cipher",
    "shift_cipher_packed",
    "vigenere_shift",
    "vigenere_unshift",
    "inclusive_scan",
    "exclusive_scan",
    "blocked_inclusive_scan",
    "BLOCKED_SCAN_THRESHOLD",
    "head_flags_from_starts",
    "segment_ids_from_starts",
    "segmented_scan",
    "segmented_scan_blocked",
    "segmented_scan_flat",
    "segmented_scan_from_starts",
    "validate_segments",
    "histogram_sort",
    "histogram_onehot",
    "histogram_segment",
    "sort",
    "sort_pairs",
    "radix_sort",
    "bitonic_sort",
    "csr_row_ids",
    "pagerank_propagate",
    "pagerank_iterate",
    "csr_spmv",
    "ell_spmv",
    "csr_to_ell",
    "transpose_pallas",
    "transpose_xla",
    "saxpy",
    "parallel_sum",
    "segmented_scan_dense",
]
