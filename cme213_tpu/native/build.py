"""On-demand build of the native sorts library.

Replaces the reference's per-unit Makefiles (``g++ -fopenmp -O3``,
``hw/hw4/programming/Makefile``) with a cached in-package build; the
``DEBUG=1`` Makefile flag (``hw/hw3/programming/Makefile:1-6``) maps to
``CME213_TPU_NATIVE_DEBUG=1``.
"""

from __future__ import annotations

import os
import subprocess
from pathlib import Path

_HERE = Path(__file__).resolve().parent
_SRCS = [_HERE / "sorts.cpp", _HERE / "io.cpp", _HERE / "spmv.cpp"]
_LIB = _HERE / "_libsorts.so"


def build_library(force: bool = False) -> Path:
    newest = max(s.stat().st_mtime for s in _SRCS)
    if not force and _LIB.exists() and _LIB.stat().st_mtime >= newest:
        return _LIB
    debug = os.environ.get("CME213_TPU_NATIVE_DEBUG") == "1"
    opt = ["-g", "-O0"] if debug else ["-O3"]
    cmd = ["g++", "-std=c++17", *opt, "-fopenmp", "-shared", "-fPIC",
           *map(str, _SRCS), "-o", str(_LIB)]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return _LIB
