"""Multi-host startup — the ``mpirun -np`` / PBS layer (strategy P12).

The reference launches distributed runs with ``mpirun -np N`` under
Torque/PBS (``hw/hw5/PA5_Handout.pdf`` §4, ``hw/hw4/programming/pa4.pbs``),
where process placement (one rank per node vs filling nodes) controls
interconnect traffic.  The JAX equivalent: each host process calls
``jax.distributed.initialize``, after which ``jax.devices()`` is the global
device list and every mesh in ``dist/mesh.py`` spans hosts transparently —
the same workload code runs 1-device, 1-host-N-device, and N-host.

Placement maps to mesh-axis ordering: axes laid out over devices on the same
host ride ICI; axes crossing hosts ride DCN.  ``make_mesh_2d`` with the
fast-varying axis within a host is the "fill each node first" configuration;
a mesh built from a host-major device ordering is "one rank per node".
"""

from __future__ import annotations

import os

#: the exact error this jaxlib's CPU backend raises when a cross-process
#: collective is attempted — a missing *capability*, not a bug in the
#: workload.  Tests and CI smokes probe worker output for it and turn the
#: run into an explicit skip; any OTHER worker error stays a hard failure.
MULTIPROCESS_UNSUPPORTED_MSG = (
    "Multiprocess computations aren't implemented on the CPU backend")


def multiprocess_unsupported(output: str) -> bool:
    """Capability probe over captured worker output: True iff the failure
    is this backend's known can't-do-multiprocess error (skip-worthy),
    False for everything else (hard-fail-worthy).  Shared by
    ``tests/test_multihost.py`` and ``scripts/faultcheck.sh`` so the skip
    criterion lives in exactly one place."""
    return MULTIPROCESS_UNSUPPORTED_MSG in output


def initialize_multihost(coordinator_address: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None) -> None:
    """Initialize the multi-host runtime (no-op on a single process).

    Arguments default from the standard env (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID) — the analog of MPI ranks coming from
    the launcher environment.
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    kwargs = {}
    # coordinator-handshake deadline (dist/launch.py --handshake-timeout):
    # a rank whose coordinator never appears must fail fast — and become
    # restartable — instead of blocking in the handshake for JAX's
    # 5-minute default
    deadline = os.environ.get("CME213_HANDSHAKE_TIMEOUT")
    if deadline:
        kwargs["initialization_timeout"] = max(1, int(float(deadline)))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


def process_info():
    """(process_id, num_processes) — the MPI_Comm_rank/size analog."""
    import jax

    return jax.process_index(), jax.process_count()
