"""hw_final workload: iterated gather-multiply-segmented-scan "SpMV" engine.

TPU-native redesign of ``hw/hw_final/programming/fp.cu``: N iterations of

    a ← segmented_inclusive_scan(a · xx)        (xx[l] = x[k[l]], precomputed)

over segments delimited by ``s`` (p entries, ``s[0]=0``, strictly increasing,
``s[p-1]=n`` — the end sentinel convention of the validating loader
``aux/mp1-util.h:81-169``).  The reference's intra-warp sliding-window scan
kernel (fp.cu:28-59) becomes the flag-based log-depth segmented scan of
``ops/segmented.py`` (or the multi-device variant in ``dist/scan.py``); the
per-iteration multiply is fused by XLA into the scan's first sweep.

Problem file formats match the reference loader (fp.cu:91-107):
``a.txt`` = ``n p q N`` then ``a`` (n floats), ``s`` (p ints), ``k`` (n
ints); ``x.txt`` = q floats — whitespace separated.

The synthetic generator mirrors ``aux/readMM.py``'s construction (random
sorted segment starts, random gather indices, uniform(−1,1) x, N ∈ [5,100]),
parameterized by (n, p, q) so problems shaped like the Bell/Garland 2008
SuiteSparse suite can be produced without the matrix files.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..core import PhaseTimer
from ..ops.segmented import (
    head_flags_from_starts,
    segmented_scan,
    segmented_scan_blocked,
    segmented_scan_dense,
    segmented_scan_flat,
    validate_segments,
)
from ..verify import golden
from ..verify.checkers import l2_distance, relative_l2_error, relative_linf_error

#: kernel names accepted by ``run_spmv_scan`` / the CLI ``--kernel=`` flag
KERNELS = ("auto", "flat", "blocked", "pallas", "pallas-fused", "dense")


@dataclass
class Problem:
    a: np.ndarray        # (n,) float values
    s: np.ndarray        # (p,) int segment starts, with end sentinel n
    k: np.ndarray        # (n,) int gather indices into x
    x: np.ndarray        # (q,) float
    iters: int

    @property
    def n(self) -> int:
        return self.a.shape[0]

    @property
    def p(self) -> int:
        return self.s.shape[0]

    @property
    def q(self) -> int:
        return self.x.shape[0]

    def validate(self) -> None:
        """Loader invariants (aux/mp1-util.h:128-148)."""
        if self.s[-1] != self.n:
            raise ValueError("last segment entry must equal n (end sentinel)")
        validate_segments(self.s[:-1], self.n)
        if ((self.k < 0) | (self.k >= self.q)).any():
            raise ValueError("gather index out of range")

    @property
    def xx(self) -> np.ndarray:
        """Gather-flattened x (the fp.cu:124-125 coalescing precompute)."""
        return self.x[self.k]


# ------------------------------------------------------------------ io

def load_problem(a_path: str, x_path: str,
                 use_native: bool = True) -> Problem:
    """Parse the reference's a.txt/x.txt problem format (fp.cu:81-107).

    Uses the native C++ tokenizer (``native.spmv_read``) when a compiler
    is available, falling back to the pure-Python parser; both produce
    identical arrays."""
    if use_native:
        import subprocess

        try:
            from .. import native

            a, s, k, q, iters = native.spmv_read(a_path)
            x = native.read_floats(x_path, q)
            prob = Problem(a, s, k, x, iters)
            prob.validate()
            return prob
        except (ImportError, OSError, RuntimeError,
                subprocess.CalledProcessError):
            pass  # no/broken toolchain or unreadable natively: fall back
    tok_a = open(a_path).read().split()
    n, p, q, iters = (int(v) for v in tok_a[:4])
    rest = tok_a[4:]
    a = np.array(rest[:n], dtype=np.float32)
    s = np.array(rest[n:n + p], dtype=np.int32)
    k = np.array(rest[n + p:n + p + n], dtype=np.int32)
    x = np.loadtxt(x_path, dtype=np.float32).reshape(-1)[:q]
    prob = Problem(a, s, k, x, iters)
    prob.validate()
    return prob


def save_problem(prob: Problem, a_path: str, x_path: str) -> None:
    with open(a_path, "w") as f:
        f.write(f"{prob.n} {prob.p} {prob.q} {prob.iters}\n")
        for arr in (prob.a, prob.s, prob.k):
            f.write(" ".join(str(v) for v in arr.tolist()) + "\n")
    with open(x_path, "w") as f:
        f.write(" ".join(str(v) for v in prob.x.tolist()) + "\n")


def generate_problem(n: int, p: int, q: int, iters: int | None = None,
                     seed: int = 0) -> Problem:
    """readMM.py-style synthetic instance: sorted random segment starts with
    0/n sentinels, random gather indices, uniform(−1,1) values."""
    rng = np.random.default_rng(seed)
    interior = np.sort(rng.choice(np.arange(1, n), size=p - 2, replace=False))
    s = np.concatenate([[0], interior, [n]]).astype(np.int32)
    k = rng.integers(0, q, size=n, dtype=np.int32)
    a = rng.uniform(-1, 1, size=n).astype(np.float32)
    x = rng.uniform(-1, 1, size=q).astype(np.float32)
    if iters is None:
        iters = int(rng.integers(5, 101))
    # Normalize the iteration's growth.  Each step applies the FIXED linear
    # map b -> segscan(b·xx); over the suite's up-to-100 iterations its
    # spectral radius compounds, and unit-scale draws overflow f32 within
    # tens of iterations on long segments (real SuiteSparse values — the
    # reference's source — are not amplifying like this).  Scaling x by
    # 1/radius makes the map growth-neutral, leaving segment structure,
    # op counts, and timings untouched.  The radius comes from a short
    # f64 power iteration using a vectorized segmented cumsum (global
    # cumsum minus per-segment offset) — accumulation order is irrelevant
    # for a radius estimate, so the serial golden isn't needed here.
    seg_lens = np.diff(s)  # s carries the end sentinel n as its last entry

    def segscan64(v):
        cs = np.cumsum(v)
        offsets = np.concatenate([[0.0], cs[s[1:-1] - 1]])
        return cs - np.repeat(offsets, seg_lens)

    xx64 = x.astype(np.float64)[k]
    b = a.astype(np.float64)
    growth = 1.0
    for _ in range(min(8, iters)):
        prev = np.abs(b).max()
        b = segscan64(b * xx64)
        cur = np.abs(b).max()
        if prev > 0 and cur > 0:
            growth = cur / prev  # last-step ratio: the aligned radius
            b /= cur             # keep the power iteration itself finite
    if np.isfinite(growth) and growth > 0:
        x = (x / growth).astype(np.float32)
    return Problem(a, s, k, x, iters)


# ------------------------------------------------------------------ engine

# the whole N-iteration loop is ONE device-resident program: a single jit
# entry whose fori_loop body fuses the multiply into the scan's first
# sweep, with the value buffer donated so XLA double-buffers in place
# instead of allocating a fresh array per iteration — no per-iteration
# Python dispatch, no per-iteration HBM allocation
_SCAN_KERNELS = {
    "auto": segmented_scan,            # size-threshold dispatch
    "flat": segmented_scan_flat,       # O(n·log n) log-sweep, bitwise-stable
    "blocked": segmented_scan_blocked,  # O(n) 3-phase block decomposition
}


def _scan_fn(scan: str, block_size: int | None):
    """The scan callable for a kernel name, with the blocked form's
    block size pinned when the caller (or the tuner) chose one —
    ``block_size`` is a jit static, so each choice is its own cached
    program."""
    if block_size is None or scan == "flat":
        return _SCAN_KERNELS[scan]
    if scan == "blocked":
        return lambda v, f: segmented_scan_blocked(v, f, block_size)
    return lambda v, f: segmented_scan(v, f, block_size=block_size)


@partial(jax.jit, static_argnames=("iters", "scan", "block_size"),
         donate_argnums=(0,))
def _iterate(a, xx, flags, iters: int, scan: str = "auto",
             block_size: int | None = None):
    scan_fn = _scan_fn(scan, block_size)

    def body(_, v):
        return scan_fn(v * xx, flags)

    return jax.lax.fori_loop(0, iters, body, a)


@partial(jax.jit, static_argnames=("iters", "scan", "block_size"),
         donate_argnums=(0,))
def _iterate_batched(a, xx, flags, iters: int, scan: str = "flat",
                     block_size: int | None = None):
    """B same-shape solves as ONE device program: ``a``/``xx``/``flags``
    are (B, n) stacks and the whole batch runs under ``jax.vmap`` of the
    single-solve loop — per-lane arithmetic is the exact expression
    ``_iterate`` runs, so each lane's result is bitwise-equal to its
    serial solve (pinned by tests/test_serve.py).  Segment structure may
    differ freely across lanes (flags are per-lane vectors); only
    ``(n, iters, dtype)`` must match, which is what the serving layer's
    shape-class buckets guarantee."""
    scan_fn = _scan_fn(scan, block_size)

    def one(v0, xxi, fi):
        def body(_, v):
            return scan_fn(v * xxi, fi)

        return jax.lax.fori_loop(0, iters, body, v0)

    return jax.vmap(one)(a, xx, flags)


def pad_problem(prob: Problem, n_to: int) -> Problem:
    """Zero-pad a problem to ``n_to`` values with the tail quarantined in
    its own segment (the ``_shard_problem`` convention): padded values are
    0·x[0] and never combine into a real segment, so the first ``n``
    outputs are bitwise-equal to the unpadded solve.  This is what lets
    degraded-mode serving merge near-sized requests into coarser
    power-of-two buckets."""
    n = prob.n
    if n_to < n:
        raise ValueError(f"cannot pad n={n} down to {n_to}")
    if n_to == n:
        return prob
    a = np.zeros(n_to, dtype=prob.a.dtype)
    a[:n] = prob.a
    k = np.zeros(n_to, dtype=prob.k.dtype)
    k[:n] = prob.k
    s = np.concatenate([prob.s[:-1], [n, n_to]]).astype(prob.s.dtype)
    return Problem(a, s, k, prob.x, prob.iters)


def run_spmv_scan_batched(probs: list[Problem], kernel: str = "flat",
                          dtype=jnp.float32) -> list[np.ndarray]:
    """Serve B same-class problems (equal ``n`` and ``iters``) from one
    jitted program — the vmap/stacking path the serving layer
    (``cme213_tpu/serve``) batches same-shape-class requests through.
    Only the XLA scans batch (``flat``/``blocked``/``auto``); per-request
    results come back unstacked, each bitwise-equal to its serial
    ``_iterate`` solve."""
    if kernel not in _SCAN_KERNELS:
        raise ValueError(f"batched serving uses the XLA kernels "
                         f"{tuple(_SCAN_KERNELS)}, not {kernel!r}")
    if not probs:
        return []
    n, iters = probs[0].n, probs[0].iters
    for p in probs:
        p.validate()
        if (p.n, p.iters) != (n, iters):
            raise ValueError(
                f"batch mixes shape classes: n{p.n}/i{p.iters} vs "
                f"n{n}/i{iters}")
    from ..core import check_op, programs, span

    a = jnp.asarray(np.stack([p.a for p in probs]), dtype)
    xx = jnp.asarray(np.stack([p.xx for p in probs]), dtype)
    # head flags built host-side in one pass: B device dispatches of
    # head_flags_from_starts would dominate the batching win for small
    # problems (each segment start is one scatter index here)
    fl = np.zeros((len(probs), n), np.int32)
    for i, p in enumerate(probs):
        fl[i, p.s[:-1]] = 1
    flags = jnp.asarray(fl)
    # the batch width is part of the compiled program, so it rides in the
    # shape class — b4 traffic never counts as a retrace of b2 traffic
    b = len(probs)
    shape_class = f"n{n}/i{iters}/b{b}"

    # the serve adapters land here: blocked/auto batches consult the
    # tuner for the measured block size of this size bucket
    tuned_block = None
    if kernel in ("auto", "blocked"):
        from ..core import tune

        tuned_block = tune.resolve(
            "spmv_scan", f"n{programs.canonical_size(n)}",
            np.dtype(dtype).name, block_size=None)["block_size"]
    static = {"iters": iters, "batch": b}
    if tuned_block is not None:
        static["block_size"] = tuned_block

    def build():
        return lambda a, xx, flags: _iterate_batched(
            a, xx, flags, iters, scan=kernel, block_size=tuned_block)

    def warm(fn):
        check_op(f"spmv_scan_batched.{kernel}",
                 fn(jnp.zeros((b, n), dtype), jnp.zeros((b, n), dtype),
                    jnp.zeros((b, n), jnp.int32)))

    runner = programs.get("spmv_scan_batched", kernel, shape_class, build,
                          dtype=np.dtype(dtype).name, warm=warm, **static)
    with span("spmv_scan_batched.run", kernel=kernel,
              shape_class=shape_class) as sp:
        out = runner(a, xx, flags)
        sp.block(out)
    out = np.asarray(out)
    return [out[i] for i in range(len(probs))]


@partial(jax.jit, static_argnames=("iters", "interpret"), donate_argnums=(0,))
def _iterate_pallas_unfused(a, xx, flags, iters: int, interpret: bool):
    """Per-iteration Pallas scan with the multiply left to XLA — one extra
    HBM round trip per iteration vs the fused kernel; kept as a bench
    point isolating what the ``fused_multiply`` hook buys."""
    from ..ops.segmented_pallas import segmented_scan_pallas

    def body(_, v):
        return segmented_scan_pallas(v * xx, flags, interpret=interpret)

    return jax.lax.fori_loop(0, iters, body, a)


@partial(jax.jit, static_argnames=("iters", "max_len"), donate_argnums=(0,))
def _iterate_dense(a, xx, starts, iters: int, max_len: int):
    """Dense strawman loop with the segment starts as a **traced** operand
    — per-problem data rides as arguments so the cached program serves any
    instance of its shape class; only ``max_len`` (padding the dense rows)
    stays static."""
    def body(_, v):
        return segmented_scan_dense(v * xx, starts, max_len)

    return jax.lax.fori_loop(0, iters, body, a)


def bytes_moved(n: int, iters: int, elem: int = 4) -> int:
    """Exact byte accounting for bandwidth reports — delegates to the
    centralized cost model (``core/roofline.spmv_scan_cost``): per
    iteration the single-pass form reads the value vector, the gathered
    ``xx`` vector, and the int32 head flags, and writes the value vector
    — ``(3·elem + 4)·n`` bytes.  Multi-sweep kernels move more than
    this; quoting all kernels against the same useful-byte count is what
    makes the GB/s column comparable (the "effective bandwidth"
    convention of ``bench.py``)."""
    from ..core.roofline import spmv_scan_cost

    dtype = {1: "u8", 2: "f16", 4: "f32", 8: "f64"}[elem]
    return spmv_scan_cost(n, iters, dtype=dtype).nbytes


#: demotion ladder per requested kernel — Pallas rungs degrade to the
#: blocked O(n) XLA scan, then to the flat log-sweep (which has no special
#: lowering requirements at all); the XLA rungs degrade straight to flat
FALLBACK_LADDERS = {
    "pallas-fused": ("pallas-fused", "blocked", "flat"),
    "pallas": ("pallas", "blocked", "flat"),
    "auto": ("auto", "flat"),
    "blocked": ("blocked", "flat"),
    "dense": ("dense", "flat"),
    "flat": ("flat",),
}

#: conformance tolerance per rung, vs the ``flat`` reference scan (probe
#: rel-L2).  ``flat`` is the reference itself; every other kernel
#: legitimately reorders the segment accumulation (blocked 3-phase
#: decomposition, Pallas blockwise carries, dense per-segment rows), so
#: bitwise equality is not its contract — the iterated-scan tolerance
#: model of ``external_check``, scaled to the tiny probe (observed probe
#: divergence is ~1e-7; a wrong kernel lands orders of magnitude out).
CONFORMANCE_REL_L2 = {
    "flat": 0.0,
    "blocked": 1e-5,
    "pallas": 1e-5,
    "pallas-fused": 1e-5,
    "dense": 1e-5,
}

#: canonical probe instance for the conformance gate: large enough to
#: exercise multi-block code paths in every kernel, small enough that the
#: one-time probe is negligible next to any real solve
_PROBE_SHAPE = dict(n=2048, p=48, q=47, iters=3, seed=1234)
_PROBE_PROBLEM: "Problem | None" = None


def _probe_problem() -> "Problem":
    global _PROBE_PROBLEM
    if _PROBE_PROBLEM is None:
        _PROBE_PROBLEM = generate_problem(**_PROBE_SHAPE)
    return _PROBE_PROBLEM


def _conformance_gate(n: int, dtype):
    """``gate(rung) -> bool`` for ``with_fallback``: first use of a
    non-reference rung (per process × dtype) runs the canonical probe
    through that rung and through ``flat``, compares to the rung's
    declared tolerance, and caches the verdict
    (``core/conformance.py``).  ``auto`` is resolved to the scan the
    size dispatch would actually pick for ``n``, so the probed kernel is
    the serving kernel."""
    from ..core import conformance
    from ..ops.segmented import scan_threshold

    def gate(rung: str) -> bool:
        kernel = rung
        if kernel == "auto":
            # the tuned-or-default crossover, so the probed kernel is
            # the one the size dispatch actually serves for this n
            kernel = "flat" if n < scan_threshold() else "blocked"
        if kernel == "flat":
            return True  # the reference rung needs no probe
        prob = _probe_problem()
        xx = jnp.asarray(prob.xx, dtype)
        flags = head_flags_from_starts(jnp.asarray(prob.s[:-1]), prob.n)

        def run(k):
            # probes compile THROUGH the program cache: gating a rung also
            # warms its program for the probe class instead of paying a
            # discarded throwaway compile
            def thunk():
                fn = _program(k, prob.n, prob.iters, dtype, p=prob.p,
                              max_len=int(np.diff(prob.s).max()))
                return np.asarray(fn(jnp.asarray(prob.a, dtype), xx, flags,
                                     jnp.asarray(prob.s[:-1])))
            return thunk

        return conformance.check(
            "spmv_scan", kernel, shape_class=np.dtype(dtype).name,
            candidate=run(kernel), reference=run("flat"),
            rel_l2=CONFORMANCE_REL_L2[kernel]).ok

    return gate


def _build_runner(kernel: str, iters: int, interpret: bool | None = None,
                  max_len: int | None = None,
                  block_size: int | None = None):
    """Shape-polymorphic runner ``fn(a, xx, flags, starts)`` executing all
    ``iters`` iterations with the named kernel.  Every per-problem array
    is an **argument** (never closed over) so the callable can live in the
    process-wide program cache and serve any problem in its shape class;
    kernels that don't need ``starts`` (everything but ``dense``) ignore
    it."""
    if kernel == "pallas-fused":
        from ..ops.segmented_pallas import spmv_scan_pallas

        return lambda a, xx, flags, starts: spmv_scan_pallas(
            a, xx, flags, iters, interpret=interpret)
    if kernel == "pallas":
        return lambda a, xx, flags, starts: _iterate_pallas_unfused(
            a, xx, flags, iters, interpret=interpret)
    if kernel in _SCAN_KERNELS:
        return lambda a, xx, flags, starts: _iterate(
            a, xx, flags, iters, scan=kernel, block_size=block_size)
    if kernel == "dense":
        return lambda a, xx, flags, starts: _iterate_dense(
            a, xx, starts, iters, max_len)
    raise ValueError(f"unknown kernel {kernel!r}")


def _program(rung: str, n: int, iters: int, dtype, p: int | None = None,
             max_len: int | None = None, block_size: int | None = None):
    """The cached program for ``(rung, n{n}/i{iters}, dtype)`` — built and
    warmed once per process (``core/programs.py``), a dict lookup ever
    after.  The warmup runs on zero inputs of the class's shapes behind
    the rung-named barrier, so compile/runtime failures surface inside
    the miss's ``spmv_scan.compile`` span attributed to the rung, exactly
    where the old per-call warmup surfaced them."""
    from ..core import check_op, programs

    static = {"iters": iters}
    interpret = None
    if rung not in ("auto", "blocked"):
        block_size = None  # a tuned block size only shapes the XLA scans
    if block_size is not None:
        # the tuned static rides in the program key: a dispatch that
        # resolves a different winner compiles (and caches) its own
        # program instead of silently reusing the old block shape
        static["block_size"] = block_size
    if rung in ("pallas", "pallas-fused"):
        interpret = jax.devices()[0].platform != "tpu"
        static["interpret"] = interpret
    if rung == "dense":
        # starts is traced, but its length and the dense row width change
        # the compiled program — they key the cache, not the closure
        static.update(p=p, max_len=max_len)

    def build():
        return _build_runner(rung, iters, interpret=interpret,
                             max_len=max_len, block_size=block_size)

    def probe_args():
        return (jnp.zeros(n, dtype), jnp.zeros(n, dtype),
                jnp.zeros(n, jnp.int32),
                jnp.zeros(max(1, (p or 1) - 1), jnp.int32))

    def warm(fn):
        check_op(f"spmv_scan.{rung}", fn(*probe_args()))

    from ..core import roofline

    return programs.get("spmv_scan", rung, f"n{n}/i{iters}", build,
                        dtype=np.dtype(dtype).name, warm=warm,
                        cost=roofline.spmv_scan_cost(n, iters, dtype=dtype),
                        probe=probe_args, **static)


def _bucket_gate(n_to: int, kernel: str, dtype) -> bool:
    """One verdict per (bucket, kernel, dtype): prove pad-and-mask is
    exact before serving from the bucket.  A probe problem inside the
    bucket is solved padded-then-sliced and unpadded; the two must be
    bitwise equal (``pad_problem``'s quarantined-tail contract — padded
    values are 0·x[0] in their own segment, so real segments never see
    them).  A failing probe keeps the caller on exact shapes —
    correctness is never traded for compile amortization."""
    from ..core import conformance

    n_from = max(2, (3 * n_to) // 4)
    if n_from >= n_to:
        return False  # bucket too small to pad into
    probe = generate_problem(n_from, p=max(3, min(9, n_from // 2)),
                             q=7, iters=2, seed=99)

    def solve(pr: Problem) -> np.ndarray:
        fn = _program(kernel, pr.n, pr.iters, dtype, p=pr.p,
                      max_len=int(np.diff(pr.s).max()))
        return np.asarray(fn(
            jnp.asarray(pr.a, dtype), jnp.asarray(pr.xx, dtype),
            head_flags_from_starts(jnp.asarray(pr.s[:-1]), pr.n),
            jnp.asarray(pr.s[:-1])))

    return conformance.check(
        "spmv_scan.pad", kernel,
        shape_class=f"n{n_to}/{np.dtype(dtype).name}",
        candidate=lambda: solve(pad_problem(probe, n_to))[:probe.n],
        reference=lambda: solve(probe), rel_l2=0.0).ok


def run_spmv_scan(prob: Problem, timer: PhaseTimer | None = None,
                  dtype=jnp.float32, kernel: str = "auto",
                  fallback: bool = True,
                  canonical: bool = False) -> np.ndarray:
    """Device pipeline (fp.cu:154-190): upload, N × (multiply + segmented
    scan), download — the N iterations run as ONE jitted ``fori_loop``
    with the value buffer donated, whatever the kernel.  Prints the
    spec-mandated timing line (Final.pdf §4.2 format, fp.cu:190).

    ``kernel``:

    - "auto" (default): XLA path, flat log-sweep below
      ``ops.BLOCKED_SCAN_THRESHOLD`` elements, blocked O(n) scan above;
    - "flat"/"blocked": force the respective XLA scan;
    - "pallas-fused": single-HBM-pass blockwise kernel with the multiply
      fused into the scan's load (``ops/segmented_pallas.py``);
    - "pallas": the same kernel per iteration but the multiply left to
      XLA (isolates the fusion win);
    - "dense": the per-segment dense-matrix strawman (the role the
      reference kept ``fp_old.cu`` around for — O(p·max_seg_len) work).

    With ``fallback`` (default), a rung that fails to compile or run —
    injected or real — demotes down ``FALLBACK_LADDERS[kernel]`` instead
    of aborting: the op completes on a working kernel and the demotion is
    recorded as structured ``rung-failed``/``served`` trace events
    (``core/resilience.with_fallback``).  The ladder also consults the
    **conformance gate**: a rung whose first-use probe diverges from the
    ``flat`` reference beyond its declared tolerance is demoted with
    ``WRONG_ANSWER`` before it can serve a silently-wrong result (verdict
    cached per process — steady state is one dict lookup).
    ``fallback=False`` keeps the reference's fail-fast behavior (and
    skips the gate — bench rows are data, not served traffic).  The
    fault-injection guard and the ladder bookkeeping run in host Python
    before the jitted loop launches, so the healthy path times
    identically.

    With ``canonical``, the request shape is snapped to its power-of-two
    bucket first (``core/programs.canonical_size``): the problem is
    zero-padded with a quarantined tail segment (``pad_problem``) and the
    output sliced back, so heterogeneous sizes share one compiled program
    per bucket.  Each (bucket, kernel, dtype) is conformance-probed once
    — padded-then-sliced must match the unpadded solve bitwise — and a
    failing probe silently falls back to the exact shape.
    """
    from ..core import programs, roofline, span, tune, with_fallback

    prob.validate()
    if canonical:
        n_to = programs.canonical_size(prob.n)
        if n_to != prob.n and _bucket_gate(n_to, kernel, dtype):
            out = run_spmv_scan(pad_problem(prob, n_to), timer=timer,
                                dtype=dtype, kernel=kernel,
                                fallback=fallback)
            return out[:prob.n]
    xx = jnp.asarray(prob.xx, dtype)
    flags = head_flags_from_starts(jnp.asarray(prob.s[:-1]), prob.n)
    starts = jnp.asarray(prob.s[:-1])
    max_len = int(np.diff(prob.s).max())
    timer = timer or PhaseTimer()

    shape_class = f"n{prob.n}/i{prob.iters}"
    cost = roofline.spmv_scan_cost(prob.n, prob.iters, dtype=dtype)

    # tuned-or-default statics (core/tune.py, keyed by the canonical
    # size bucket): "auto" serves the measured kernel choice and the
    # blocked scans serve the measured block size; ``CME213_TUNE=0`` or
    # an empty cache leaves every default in place
    tuned_block = None
    if kernel in ("auto", "blocked"):
        bucket = f"n{programs.canonical_size(prob.n)}"
        if kernel == "auto":
            t = tune.resolve("spmv_scan", bucket, np.dtype(dtype).name,
                             kernel="auto", block_size=None)
            if t["kernel"] in ("flat", "blocked"):
                kernel = t["kernel"]
            tuned_block = t["block_size"]
        else:
            tuned_block = tune.resolve("spmv_scan", bucket,
                                       np.dtype(dtype).name,
                                       block_size=None)["block_size"]

    def attempt(rung: str):
        def thunk():
            # the process-wide program cache replaces the old per-call
            # closure + warmup: a miss builds and warms inside the
            # spmv_scan.compile span (feeding the per-shape-class
            # compile.ms histogram and the retrace detector, with
            # failures surfacing attributed to the rung before the timed
            # phase opens — the CUDA analog timed only kernel execution
            # between cudaEvents); a hit is one dict lookup, so a second
            # call on a known shape class performs zero retraces
            runner = _program(rung, prob.n, prob.iters, dtype, p=prob.p,
                              max_len=max_len, block_size=tuned_block)
            # every kernel donates its value buffer, so each attempt gets
            # a fresh host->device upload — a rung that dies mid-run must
            # not leave the next rung a donated (invalid) buffer
            a = jnp.asarray(prob.a, dtype)
            with span("spmv_scan.run", kernel=rung, n=prob.n,
                      iters=prob.iters, shape_class=shape_class) as sp:
                sp.roofline(cost.nbytes, cost.flops)
                with timer.phase("spmv_scan") as ph:
                    out = runner(a, xx, flags, starts)
                    ph.block(out)
            return out
        return thunk

    rungs = FALLBACK_LADDERS[kernel] if fallback else (kernel,)
    gate = _conformance_gate(prob.n, dtype) if fallback else None
    res = with_fallback("spmv_scan", [(r, attempt(r)) for r in rungs],
                        gate=gate)
    if res.demoted:
        print(f"spmv_scan: kernel {kernel!r} demoted to {res.rung!r} "
              f"(failed: {', '.join(f.rung for f in res.failures)})")
    ms = timer.last_ms("spmv_scan")
    print(f"The running time of my code for {prob.iters} iterations is: "
          f"{ms} milliseconds.")
    return np.asarray(res.value)


def run_spmv_scan_checkpointed(prob: Problem, path: str, every: int = 0,
                               kernel: str = "auto", dtype=jnp.float32,
                               max_retries: int = 1) -> np.ndarray:
    """Long-solve form of the engine: the N iterations run in checkpointed
    chunks of ``every`` with a finiteness guard on each chunk (host-side,
    outside the jitted ``fori_loop`` — zero overhead inside the hot loop).

    A NaN blow-up (injected via ``CME213_FAULTS=nan:spmv_scan`` or real)
    rolls back to the last good checksummed checkpoint and retries the
    chunk; a killed process resumes from ``path`` on relaunch.  Chunking is
    deterministic, so an interrupted-and-resumed solve is bitwise equal to
    an uninterrupted one with the same ``every``.  ``kernel`` must be one
    of the XLA scans (auto/flat/blocked).

    Memory pressure degrades instead of dying: the first chunk is
    **preflighted** against the memory budget
    (``core/admission.preflight`` — a resident set the budget can never
    hold is refused up front with a structured ``admission-rejected``
    record), and a chunk that still dies ``RESOURCE_EXHAUSTED`` at
    runtime (real, or ``CME213_FAULTS=oom:spmv_scan_chunk``) is halved
    and retried from the last checkpoint — bitwise-neutral, since every
    iteration runs the same program whatever the chunk boundaries.
    """
    from ..core import admission
    from ..core.checkpoint import run_with_checkpoints
    from ..core.numerics import ConvergenceTracker
    from ..core.resilience import all_finite

    if kernel not in _SCAN_KERNELS:
        raise ValueError(f"checkpointed runs use the XLA kernels "
                         f"{tuple(_SCAN_KERNELS)}, not {kernel!r}")
    prob.validate()
    xx = jnp.asarray(prob.xx, dtype)
    flags = head_flags_from_starts(jnp.asarray(prob.s[:-1]), prob.n)
    a0 = jnp.asarray(prob.a, dtype)
    every = every or prob.iters
    decision = admission.preflight(
        _iterate, jnp.zeros_like(a0), xx, flags, op="spmv_scan",
        iters=min(every, prob.iters), scan=kernel)
    if not decision.admitted:
        raise admission.AdmissionError(f"spmv_scan: {decision.detail}")

    starts = jnp.asarray(prob.s[:-1])

    def step(state, k):
        # per-chunk-size programs come from the process-wide cache: a
        # resumed or retried solve re-running a chunk length it has seen
        # is a dict lookup, not a recompile
        fn = _program(kernel, prob.n, k, dtype, p=prob.p)
        return fn(jnp.asarray(state, dtype), xx, flags, starts)

    # the iterated gather·multiply is not a decaying solve — its state
    # can legitimately plateau — so the stall window is kept loose: only
    # a residual flat across many chunks reads as STALLED
    out = run_with_checkpoints(step, a0, prob.iters,
                               path, every=every, guard=all_finite,
                               op="spmv_scan", max_retries=max_retries,
                               tracker=ConvergenceTracker(
                                   "spmv_scan", stall_epochs=8))
    return np.asarray(out)


def run_spmv_scan_distributed(prob: Problem, mesh, dtype=jnp.float32,
                              timer: PhaseTimer | None = None) -> np.ndarray:
    """Mesh-parallel pipeline: the value sequence is sharded over the mesh's
    first axis and each iteration runs the multi-device segmented scan
    (``dist/scan.py``) — the long-sequence scaling path.  The per-shard
    scan inherits the flat/blocked size dispatch, so per-shard work is
    O(n/d) once shards cross the threshold.  Pads to a shard multiple
    with zero-valued, own-segment tail elements (they never affect real
    segments).  The carry-combine backend is conformance-gated
    (``dist/scan.make_iterated_sharded_scan_gated``): ring demotes to
    gather if its probe diverges."""
    from ..dist.scan import make_iterated_sharded_scan_gated

    prob.validate()
    a_d, xx_d, fl_d, n = _shard_problem(prob, mesh, dtype)
    iterate, _ = make_iterated_sharded_scan_gated(mesh)

    timer = timer or PhaseTimer()
    iterate(jnp.zeros_like(a_d), xx_d, fl_d, prob.iters).block_until_ready()
    with timer.phase("spmv_scan_distributed") as ph:
        out = iterate(a_d, xx_d, fl_d, prob.iters)
        ph.block(out)
    return np.asarray(out)[:n]


def _shard_problem(prob: Problem, mesh, dtype, values: np.ndarray | None = None):
    """Pad + shard the problem state over the mesh's first axis: returns
    ``(a, xx, flags, n)`` device arrays.  ``values`` overrides the value
    vector (the resume path re-shards a committed mid-solve state)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    axis = mesh.axis_names[0]
    nshards = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    n = prob.n
    padded = -(-n // nshards) * nshards
    a = np.zeros(padded, dtype=np.float32)
    a[:n] = prob.a if values is None else values
    xx = np.zeros(padded, dtype=np.float32)
    xx[:n] = prob.xx
    flags = np.zeros(padded, dtype=np.int32)
    flags[prob.s[:-1]] = 1
    if padded > n:
        flags[n] = 1  # quarantine the tail in its own segment

    sharding = NamedSharding(mesh, P(axis))
    return (jax.device_put(jnp.asarray(a, dtype), sharding),
            jax.device_put(jnp.asarray(xx, dtype), sharding),
            jax.device_put(jnp.asarray(flags), sharding), n)


def _problem_crc(prob: Problem) -> int:
    """CRC32 over the problem's defining arrays — pins a commit to ITS
    problem instance so a resume can't silently mix solves."""
    import zlib

    crc = 0
    for arr in (prob.a, prob.s, prob.k, prob.x):
        crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return crc & 0xFFFFFFFF


def run_spmv_scan_distributed_supervised(prob: Problem, mesh, ckpt_dir: str,
                                         every: int = 0, dtype=jnp.float32,
                                         resume: bool = True,
                                         heartbeat=None) -> np.ndarray:
    """Supervised form of the mesh-parallel pipeline: the sharded value
    vector is epoch-committed (``dist/ckpt.py``) every ``every``
    iterations with a heartbeat per epoch, and ``resume`` reloads the
    newest valid commit — **elastically**: the commit stores the true
    (n,)-length state plus its shard map, so a solve committed on a
    2-shard mesh resumes on 4 shards (and vice versa), re-padded and
    re-sharded for the new axis size.  ``faults.maybe_kill_rank`` guards
    each epoch boundary, mirroring the supervised heat solve.

    Same-mesh resume is bitwise; across shard counts the carry-combine
    order changes, so results match the single-device reference to the
    usual scan tolerance instead.

    An epoch chunk that dies ``RESOURCE_EXHAUSTED`` (real, or
    ``CME213_FAULTS=oom:spmv_scan_chunk``) halves ``every``, re-shards
    from the last committed state, and retries — the distributed form of
    the checkpointed solve's chunk-shrink response.
    """
    from ..core import metrics
    from ..core.faults import maybe_kill_rank, maybe_oom
    from ..core.resilience import FailureKind, classify_failure
    from ..core.trace import record_event
    from ..dist.ckpt import check_meta, commit_epoch, load_latest_commit
    from ..dist.scan import make_iterated_sharded_scan_gated

    prob.validate()
    meta = {"kind": "spmv_scan", "n": prob.n, "iters": prob.iters,
            "problem_crc": _problem_crc(prob),
            "dtype": np.dtype(dtype).name}
    every = every or prob.iters
    process_id, process_count = 0, 1
    if jax.process_count() > 1:
        process_id, process_count = jax.process_index(), jax.process_count()

    def load_state(force: bool = False):
        # the chunk-shrink retry always reloads (its own commits from this
        # run are durable even when the solve started with resume=False)
        loaded = load_latest_commit(ckpt_dir) if (resume or force) else None
        if loaded is None:
            return 0, 0, None
        manifest, committed = loaded
        check_meta(manifest, **meta)
        return manifest["step"], manifest["epoch"], np.asarray(committed)

    start, epoch, values = load_state()
    a_d, xx_d, fl_d, n = _shard_problem(prob, mesh, dtype, values=values)
    iterate, _ = make_iterated_sharded_scan_gated(mesh)
    if heartbeat is not None:
        heartbeat.beat(start)
    it = start
    while it < prob.iters:
        maybe_kill_rank(step=epoch)
        k = min(every, prob.iters - it)
        try:
            maybe_oom("spmv_scan_chunk")
            a_new = iterate(a_d, xx_d, fl_d, k)
            jax.block_until_ready(a_new)
        except Exception as e:  # noqa: BLE001 — classify, then decide
            if classify_failure(e) is not FailureKind.RESOURCE or k <= 1:
                raise
            every = max(1, k // 2)
            metrics.counter("admission.chunk_shrunk").inc()
            record_event("chunk-shrunk", op="spmv_scan", from_size=k,
                         to_size=every, reason=type(e).__name__)
            # the chunk may have consumed its donated shard buffers —
            # rebuild from the last committed state (or the problem)
            it, epoch, values = load_state(force=True)
            a_d, xx_d, fl_d, n = _shard_problem(prob, mesh, dtype,
                                                values=values)
            continue
        a_d = a_new
        it += k
        epoch += 1
        commit_epoch(ckpt_dir, epoch, it, a_d, true_shape=(n,), meta=meta,
                     process_id=process_id, process_count=process_count)
        if heartbeat is not None:
            heartbeat.beat(it)
    return np.asarray(a_d)[:n]


# ------------------------------------------------------------------ checking

def external_check(prob: Problem, result: np.ndarray) -> dict:
    """Double-precision serial checker — the reference's external grader
    (``aux/reference_spMVscan-released.cu:38-54,65-144``): recompute in f64
    and report absolute+relative L2 and L∞ errors."""
    ref = golden.host_spmv_scan(prob.a, prob.s[:-1], prob.xx, prob.iters,
                                dtype=np.float64)
    return {
        "l2": l2_distance(ref, result),
        "rel_l2": relative_l2_error(ref, result),
        "rel_linf": relative_linf_error(ref, result),
    }


# ------------------------------------------------------------------ suite

# Problems shaped like the Bell/Garland 2008 SuiteSparse suite the reference
# benchmarks (names + the reference's per-matrix iteration counts from
# ``paper/Final_Report_DongBang_Tsai.tex:236-251``; n = nnz-scale, p = row
# count, approximated — generated synthetically the way readMM.py generated
# instances from the real matrix files).
BELL_GARLAND_SUITE = {
    # name: (n, p, q, iters)
    "cant": (4_007_383, 62_452, 62_451, 50),
    "consph": (6_010_480, 83_335, 83_334, 20),
    "cop20k_A": (2_624_331, 121_193, 121_192, 73),
    "dense2": (4_000_000, 2_001, 2_000, 10),
    "jonheart": (127_224, 1_780, 1_779, 60),
    "mac_econ_fwd500": (1_273_389, 206_501, 206_500, 12),
    "mc2depi": (2_100_225, 525_826, 525_825, 70),
    "pdb1HYS": (4_344_765, 36_418, 36_417, 30),
    "pwtk": (11_634_424, 217_919, 217_918, 25),
    "qcd5_4": (1_916_928, 49_153, 49_152, 63),
    "rail4284": (11_279_748, 4_285, 4_284, 10),
    "rma10": (2_374_001, 46_836, 46_835, 74),
    "scircuit": (958_936, 170_999, 170_998, 30),
    "shipsec1": (7_813_404, 140_875, 140_874, 10),
    "webbase-1M": (3_105_536, 1_000_006, 1_000_005, 77),
}


def suite_problem(name: str, seed: int = 0, scale: float = 1.0) -> Problem:
    """Generate the named suite instance (``scale`` < 1 shrinks dims
    proportionally for quick runs)."""
    n, p, q, iters = BELL_GARLAND_SUITE[name]
    n = max(16, int(n * scale))
    p = max(3, min(int(p * scale), n - 1))
    q = max(2, int(q * scale))
    return generate_problem(n, p, q, iters, seed=seed)


# ------------------------------------------------------------------ CLI

def main(argv: list[str]) -> int:
    """Driver CLI mirroring the reference's fp binary (fp.cu:74-216) plus a
    readMM-style ``gen`` subcommand:

        spmv_scan a.txt x.txt [cpu_check]
                  [--kernel=auto|flat|blocked|pallas|pallas-fused|dense]
                  [--distributed] [--canonical]
        spmv_scan gen a.txt x.txt [n p q [iters]] [--seed=S]
        spmv_scan mtx matrix.mtx [cpu_check] [--kernel=...] [--seed=S]

    The run form loads the problem, executes the device pipeline (printing
    the spec-mandated timing line), writes ``b.txt`` (one value per line,
    via the native writer when available), and with ``cpu_check`` also
    writes ``b_cpu.txt`` and applies the 1e-2 tolerance compare
    (fp.cu:192-212).
    """
    args = [a for a in argv[1:] if not a.startswith("--")]
    kernel = "auto"
    seed = 0
    distributed = False
    canonical = False
    for a in argv[1:]:
        if a.startswith("--kernel="):
            kernel = a.split("=", 1)[1]
        elif a.startswith("--seed="):
            seed = int(a.split("=", 1)[1])
        elif a == "--distributed":
            distributed = True
        elif a == "--canonical":
            canonical = True
        elif a.startswith("--"):
            print(f"error: unknown option {a!r} (flags use --name=value)")
            return 2
    if kernel not in KERNELS:
        print(f"error: unknown kernel {kernel!r} ({'|'.join(KERNELS)})")
        return 2

    if args and args[0] == "gen":
        if len(args) not in (3, 6, 7):
            print("usage: spmv_scan gen a.txt x.txt [n p q [iters]] "
                  "[--seed=S]")
            return 2
        a_path, x_path = args[1], args[2]
        if len(args) >= 6:
            n, p, q = int(args[3]), int(args[4]), int(args[5])
            iters = int(args[6]) if len(args) > 6 else None
        else:
            n, p, q, iters = 100_000, 1_000, 999, None
        prob = generate_problem(n, p, q, iters, seed=seed)
        save_problem(prob, a_path, x_path)
        print(f"wrote {a_path} (n={prob.n} p={prob.p} q={prob.q} "
              f"N={prob.iters}) and {x_path}")
        return 0

    cpu_check = len(args) > 2 and args[2] not in ("0", "false")
    if args and args[0] == "mtx":
        # readMM.py parity path: build the instance straight from a real
        # MatrixMarket file (aux/readMM.py:16-63) and fall through to the
        # normal run (b.txt, timing line, optional f64 check)
        if len(args) < 2:
            print("usage: spmv_scan mtx matrix.mtx [cpu_check] "
                  "[--kernel=...] [--seed=S]")
            return 2
        from .matrix_market import dense2_problem, problem_from_mtx

        try:
            if args[1] == "dense2":
                # built-in reconstruction: the dense 2000×2000 instance is
                # fully pattern-determined, built in memory instead of via
                # a ~60 MB .mtx text detour (see matrix_market.dense2_problem)
                prob = dense2_problem(iters=None, seed=seed)
            else:
                prob = problem_from_mtx(args[1], seed=seed)
        except (OSError, ValueError, IndexError) as e:
            print(f"error: cannot load matrix: {e}")
            return 2
        print(f"loaded {args[1]}: n={prob.n} p={prob.p} q={prob.q} "
              f"N={prob.iters}")
    else:
        if len(args) < 2:
            print(__doc__)
            print(main.__doc__)
            return 2
        a_path, x_path = args[0], args[1]
        try:
            prob = load_problem(a_path, x_path)
        except (OSError, ValueError, IndexError) as e:
            print(f"error: cannot load problem: {e}")
            return 2
    if distributed:
        from ..dist import make_mesh_1d

        ndev = len(jax.devices())
        timer = PhaseTimer()
        out = run_spmv_scan_distributed(prob, make_mesh_1d(ndev),
                                        timer=timer)
        ms = timer.last_ms("spmv_scan_distributed")
        print(f"The running time of my code for {prob.iters} iterations "
              f"is: {ms} milliseconds. ({ndev} devices)")
    else:
        out = run_spmv_scan(prob, kernel=kernel, canonical=canonical)

    def write_out(path: str, values: np.ndarray) -> None:
        try:
            from .. import native

            native.write_floats(path, values)
        except Exception:
            with open(path, "w") as f:
                for v in np.asarray(values, np.float32):
                    f.write(f"{v:.9g}\n")

    write_out("b.txt", out)
    rc = 0
    if cpu_check:
        # one f64 golden run serves both the b_cpu.txt dump and the
        # checker metrics (external_check would recompute it)
        ref = golden.host_spmv_scan(prob.a, prob.s[:-1], prob.xx,
                                    prob.iters, dtype=np.float64)
        write_out("b_cpu.txt", ref.astype(np.float32))
        # pass/fail on the norm-relative metrics of the reference's
        # external double-precision checker (its README concedes the flat
        # 1e-2 band of fp.cu:193-206 leaves rounding slack: iterated scans
        # grow magnitudes, so only normwise error is meaningful)
        errs = {"l2": l2_distance(ref, out),
                "rel_l2": relative_l2_error(ref, out),
                "rel_linf": relative_linf_error(ref, out)}
        print(f"abs L2 {errs['l2']:.3e}  rel L2 {errs['rel_l2']:.3e}  "
              f"rel Linf {errs['rel_linf']:.3e}")
        if errs["rel_l2"] <= 1e-4 and errs["rel_linf"] <= 1e-3:
            print("Worked! device and reference output match.")
        else:
            print("MISMATCH: normwise error exceeds tolerance "
                  "(rel L2 > 1e-4 or rel Linf > 1e-3)")
            rc = 1
    return rc


if __name__ == "__main__":
    import sys

    raise SystemExit(main(sys.argv))
