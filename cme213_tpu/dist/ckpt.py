"""Epoch-committed distributed checkpoints — the cross-rank resume layer.

``core/checkpoint.py`` hardens a *single-process* solve; this module extends
that story across a gang: every rank writes its grid shard for epoch E
(reusing the CRC32-checksummed ``.npz`` payload format), the ranks agree the
writes finished, then rank 0 atomically publishes a ``COMMIT`` manifest
recording epoch, world size, grid decomposition and per-shard checksums.
The commit point is the single ``os.replace`` of the manifest: a crash
*anywhere* in the window — mid-shard-write, between shard write and publish
(injectable via ``CME213_FAULTS=ckpt:commit``), or mid-manifest-write
(``ckpt:truncate``) — leaves the previous ``COMMIT`` in place, so resume
always lands on a globally consistent epoch, never a torn one.

Orbax-style layout under the checkpoint directory::

    <dir>/epoch_00000002/shard_0_0.npz     per-shard checksummed payloads
    <dir>/epoch_00000002/shard_32_0.npz    (named by global start offsets)
    <dir>/COMMIT                           JSON manifest of the live epoch
    <dir>/COMMIT.prev                      previous committed epoch

Write-completion agreement is file-based (and therefore works on backends
without cross-process collectives, e.g. this jaxlib's CPU backend): rank 0
reads back and checksum-validates *every* shard file — its own included, so
a torn local write is caught **before** publish, not at resume — and the
other ranks block until the manifest for their epoch appears.  On backends
with working multiprocess collectives a psum barrier would subsume the
polling; the file protocol is the portable lowest common denominator and
what the crash-window tests pin.

Elastic resume: ``load_latest_commit`` reassembles the *global* array from
the manifest's shard map, so a commit written on a 2-device mesh restores
onto a 4-device mesh (or 2-D blocks onto 1-D stripes) — callers re-decompose
the returned global array for whatever mesh they now hold.
"""

from __future__ import annotations

import json
import os
import shutil
import time

import numpy as np

from ..core import metrics
from ..core.checkpoint import CheckpointCorrupt, read_checkpoint, save_checkpoint
from ..core.faults import maybe_fail_commit, maybe_truncate_file
from ..core.trace import record_event, span

#: manifest filename of the live committed epoch (atomic-replace published)
COMMIT_NAME = "COMMIT"
#: retained previous committed manifest (same rotation as checkpoint .prev)
PREV_SUFFIX = ".prev"

_FORMAT = 1


class CommitError(RuntimeError):
    """The commit protocol cannot proceed (torn shard, lost peer, bad meta)."""


def epoch_dirname(epoch: int) -> str:
    return f"epoch_{int(epoch):08d}"


def _norm_index(index, shape) -> tuple[tuple[int, int], ...]:
    """Normalize a shard index (tuple of slices, Nones for full span) to
    ((start, stop), ...) against the global shape."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def shard_filename(index: tuple[tuple[int, int], ...]) -> str:
    """Deterministic shard filename from the global start offsets."""
    return "shard_" + "_".join(str(lo) for lo, _ in index) + ".npz"


def global_shard_map(array) -> list[tuple[tuple[int, int], ...]]:
    """Every shard's global index range, deduplicated across replicas and
    deterministically ordered — identical on every process (it derives from
    the sharding, not from addressability)."""
    imap = array.sharding.devices_indices_map(array.shape)
    seen = sorted({_norm_index(idx, array.shape) for idx in imap.values()})
    return seen


def write_epoch_shards(ckpt_dir: str, epoch: int, step: int, array) -> dict:
    """Write this process's addressable shards of ``array`` into the epoch
    directory (checksummed payload format).  Returns ``{filename: crc}`` of
    the shards written here (replica 0 only — replicated shards are written
    once)."""
    edir = os.path.join(ckpt_dir, epoch_dirname(epoch))
    os.makedirs(edir, exist_ok=True)
    written = {}
    for shard in array.addressable_shards:
        if shard.replica_id != 0:
            continue
        index = _norm_index(shard.index, array.shape)
        fname = shard_filename(index)
        crc = save_checkpoint(os.path.join(edir, fname), step,
                              shard=np.asarray(shard.data))
        written[fname] = crc
    return written


def _validate_shard(path: str, step: int, deadline: float) -> int:
    """Wait for ``path`` to exist (a peer may still be writing), then
    checksum-validate it.  Shard writes land by atomic rename, so an
    *existing* file that fails validation is torn for good — fail
    immediately rather than burn the deadline."""
    while not os.path.exists(path):
        if time.monotonic() > deadline:
            raise CommitError(f"shard never appeared: {path} "
                              f"(peer dead or stalled?)")
        time.sleep(0.02)
    try:
        got_step, _, crc = read_checkpoint(path)
    except Exception as e:
        raise CommitError(f"torn shard {path}: {type(e).__name__}: {e}") from e
    if got_step != step:
        raise CommitError(f"shard {path} carries step {got_step}, "
                          f"expected {step} (stale epoch dir?)")
    return crc


def commit_epoch(ckpt_dir: str, epoch: int, step: int, array,
                 true_shape: tuple[int, ...], meta: dict | None = None,
                 process_id: int = 0, process_count: int = 1,
                 timeout: float = 120.0) -> dict | None:
    """Run one round of the commit protocol for ``array`` (a sharded or
    single-device jax array) at iteration ``step``.

    Every process writes its shards; rank 0 then validates the complete
    shard set (checksums + step stamp — the write-finished agreement) and
    atomically publishes the ``COMMIT`` manifest, rotating the previous one
    to ``COMMIT.prev``; other ranks block until the manifest for this epoch
    is visible, so the gang leaves the protocol in lockstep.  Returns the
    manifest on rank 0, None elsewhere.

    ``true_shape`` records the unpadded logical extent (e.g. the heat
    solve's (ny, nx) under ghost padding) so elastic resume can trim before
    re-decomposing.  ``meta`` rides in the manifest verbatim for caller
    sanity checks (``check_meta``).

    The whole protocol round runs inside a ``ckpt.commit`` span (per rank:
    on followers it measures shard write + manifest wait); rank 0's
    ``epoch-commit`` event and the ``commit.ms`` histogram carry the
    write→validate→publish latency the ``trace`` CLI reports percentiles
    over.
    """
    t0 = time.perf_counter()
    with span("ckpt.commit", epoch=int(epoch), step=int(step)):
        deadline = time.monotonic() + timeout
        own = write_epoch_shards(ckpt_dir, epoch, step, array)
        if process_id != 0:
            _wait_for_commit(ckpt_dir, epoch, deadline)
            return None

        edir = os.path.join(ckpt_dir, epoch_dirname(epoch))
        entries = []
        for index in global_shard_map(array):
            fname = shard_filename(index)
            # validate every file by read-back — own shards included, so a
            # torn local write aborts the commit here, not poisons resume
            crc = _validate_shard(os.path.join(edir, fname), step, deadline)
            entries.append({"file": fname, "index": [list(r) for r in index],
                            "crc": crc})
        manifest = {
            "format": _FORMAT,
            "epoch": int(epoch),
            "step": int(step),
            "world": int(process_count),
            "epoch_dir": epoch_dirname(epoch),
            "global_shape": [int(d) for d in array.shape],
            "true_shape": [int(d) for d in true_shape],
            "dtype": str(array.dtype),
            "meta": dict(meta or {}),
            "shards": entries,
        }
        # the crash window under test: shards durable, manifest not yet live
        maybe_fail_commit()
        _publish(ckpt_dir, manifest)
        ms = round((time.perf_counter() - t0) * 1e3, 3)
        metrics.counter("commit.epochs").inc()
        metrics.histogram("commit.ms").observe(ms)
        record_event("epoch-commit", epoch=int(epoch), step=int(step),
                     world=int(process_count), shards=len(entries), ms=ms)
        _gc_epochs(ckpt_dir)
        return manifest


def _publish(ckpt_dir: str, manifest: dict) -> None:
    """The commit point: tmp-write the manifest, rotate, atomic replace."""
    path = os.path.join(ckpt_dir, COMMIT_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    maybe_truncate_file(tmp)  # injected torn manifest (no-op without faults)
    if os.path.exists(path):
        os.replace(path, path + PREV_SUFFIX)
    os.replace(tmp, path)


def _wait_for_commit(ckpt_dir: str, epoch: int, deadline: float) -> None:
    path = os.path.join(ckpt_dir, COMMIT_NAME)
    while True:
        try:
            with open(path) as f:
                if json.load(f).get("epoch", -1) >= epoch:
                    return
        except (OSError, ValueError):
            pass  # not published yet (or mid-rotation); keep waiting
        if time.monotonic() > deadline:
            raise CommitError(
                f"COMMIT for epoch {epoch} never published (rank 0 dead?)")
        time.sleep(0.02)


def _gc_epochs(ckpt_dir: str) -> None:
    """Drop epoch directories older than the two committed generations
    (COMMIT + COMMIT.prev) — never a newer one a peer may be writing."""
    keep = set()
    floor = None
    for name in (COMMIT_NAME, COMMIT_NAME + PREV_SUFFIX):
        try:
            with open(os.path.join(ckpt_dir, name)) as f:
                m = json.load(f)
            keep.add(m["epoch_dir"])
            floor = m["epoch"] if floor is None else min(floor, m["epoch"])
        except (OSError, ValueError, KeyError):
            continue
    if floor is None:
        return
    for name in os.listdir(ckpt_dir):
        if not name.startswith("epoch_") or name in keep:
            continue
        try:
            num = int(name.split("_", 1)[1])
        except ValueError:
            continue
        if num < floor:
            shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)


# ---------------------------------------------------------------- resume

def _load_manifest(path: str) -> dict:
    with open(path) as f:
        m = json.load(f)
    if m.get("format") != _FORMAT:
        raise CheckpointCorrupt(f"unknown manifest format {m.get('format')!r}")
    for key in ("epoch", "step", "epoch_dir", "global_shape", "true_shape",
                "dtype", "shards"):
        if key not in m:
            raise CheckpointCorrupt(f"manifest missing {key!r}")
    return m


def _assemble(ckpt_dir: str, manifest: dict) -> np.ndarray:
    """Reassemble the global array from the manifest's shard map,
    checksum-pinning every shard to the manifest, and trim ghost padding
    to ``true_shape``."""
    out = np.empty(tuple(manifest["global_shape"]),
                   dtype=np.dtype(manifest["dtype"]))
    covered = 0
    edir = os.path.join(ckpt_dir, manifest["epoch_dir"])
    for entry in manifest["shards"]:
        step, arrays, _ = read_checkpoint(os.path.join(edir, entry["file"]),
                                          expect_crc=entry["crc"])
        if step != manifest["step"]:
            raise CheckpointCorrupt(
                f"shard {entry['file']} step {step} != "
                f"manifest step {manifest['step']}")
        index = tuple(slice(lo, hi) for lo, hi in entry["index"])
        block = arrays["shard"]
        want = tuple(hi - lo for lo, hi in entry["index"])
        if block.shape != want:
            raise CheckpointCorrupt(
                f"shard {entry['file']} shape {block.shape} != index {want}")
        out[index] = block
        covered += block.size
    if covered != out.size:
        raise CheckpointCorrupt(
            f"shard map covers {covered} of {out.size} elements")
    return out[tuple(slice(0, d) for d in manifest["true_shape"])]


def load_latest_commit(ckpt_dir: str):
    """Resume point: ``(manifest, global_array)`` from the newest *valid*
    committed epoch, or None when nothing is recoverable.

    Tries ``COMMIT`` then ``COMMIT.prev``; a torn manifest or any
    checksum-failing / missing / misshapen shard invalidates the whole
    candidate epoch (commits are all-or-nothing) with a structured
    ``commit-invalid`` event, and the previous generation is tried.
    """
    for name in (COMMIT_NAME, COMMIT_NAME + PREV_SUFFIX):
        path = os.path.join(ckpt_dir, name)
        if not os.path.exists(path):
            continue
        try:
            manifest = _load_manifest(path)
            restored = _assemble(ckpt_dir, manifest)
        except Exception as e:  # torn manifest/shard: fall back a generation
            metrics.counter("commit.invalid").inc()
            record_event("commit-invalid", candidate=name,
                         error=type(e).__name__, message=str(e)[:200])
            continue
        record_event("commit-loaded", epoch=manifest["epoch"],
                     step=manifest["step"], candidate=name)
        return manifest, restored
    return None


def check_meta(manifest: dict, **expected) -> None:
    """Pin resume to a compatible solve: every ``expected`` key must match
    the manifest's ``meta`` verbatim.  World size and mesh shape are
    deliberately NOT pinned — that is the elastic axis."""
    meta = manifest.get("meta", {})
    bad = {k: (meta.get(k), v) for k, v in expected.items()
           if meta.get(k) != v}
    if bad:
        detail = ", ".join(f"{k}: committed {got!r} != current {want!r}"
                           for k, (got, want) in bad.items())
        raise CommitError(f"commit incompatible with this solve ({detail})")
