"""``python -m cme213_tpu chaos`` — game-day chaos campaigns.

Four subcommands over :mod:`cme213_tpu.core.chaos`:

- ``run``: N seeded campaigns — draw a fault cocktail, arm it against a
  live serving run (in-process server or a real replica fleet), check
  the five global invariants, ddmin-shrink any violation to a minimal
  cocktail and bank it as a replayable fixture.  Exit 0 iff every
  campaign held every invariant.
- ``draw``: print the cocktails N campaigns *would* arm, without
  running anything — the CI determinism gate diffs two draws of the
  same seed.
- ``replay``: re-run banked fixtures; exit 0 iff every fixture's
  observed violations match its recorded expectation.
- ``matrix``: print the clause-compatibility matrix, including why the
  ineligible fault kinds are excluded.

Example (the CI chaos gate)::

    python -m cme213_tpu chaos run --seed 1 --campaigns 8 \\
        --backend fleet --replicas 2 --mix cipher,sort,heat --json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def _add_campaign_flags(ap: argparse.ArgumentParser) -> None:
    ap.add_argument("--seed", type=int, default=0,
                    help="campaign seed; cocktails are a pure function "
                    "of (seed, campaign index)")
    ap.add_argument("--campaigns", type=int, default=4,
                    help="number of seeded campaigns to run")
    ap.add_argument("--backend", choices=("inproc", "fleet"),
                    default="inproc",
                    help="inproc: in-process server (fast); fleet: live "
                    "replica subprocesses behind the socket front end")
    ap.add_argument("--mix", default="cipher,sort",
                    help="loadgen op mix the cocktail is armed against")
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--replicas", type=int, default=2,
                    help="fleet backend: replica count")
    ap.add_argument("--max-batch", type=int, default=4)


def _run_main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos run",
        description="run seeded chaos campaigns against a live serving "
                    "run and check the global invariants")
    _add_campaign_flags(ap)
    ap.add_argument("--no-shrink", action="store_true",
                    help="report violations without ddmin-shrinking them")
    ap.add_argument("--bank-dir", default=None,
                    help="fixture directory (default tests/chaos_fixtures/)")
    ap.add_argument("--disable", action="append", default=[],
                    metavar="FEATURE",
                    help="game-day handicap: switch off one resilience "
                    "behaviour for the drill (know: drift-compensation)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    from .core import chaos

    try:
        out = chaos.run_campaigns(
            seed=args.seed, campaigns=args.campaigns,
            backend=args.backend, mix=args.mix, requests=args.requests,
            replicas=args.replicas, max_batch=args.max_batch,
            shrink_violations=not args.no_shrink,
            bank_dir=args.bank_dir, handicaps=tuple(args.disable))
    except ValueError as e:
        print(f"chaos run: {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(out, indent=2))
    else:
        for c in out["campaigns"]:
            mark = "ok  " if c["ok"] else "FAIL"
            print(f"campaign {c['campaign']:>2} [{mark}] {c['cocktail']}")
            for v in c["violations"]:
                print(f"    {v['invariant']}: {v['detail']}")
        for path in out["fixtures"]:
            print(f"banked {path}")
        print(f"{len(out['campaigns'])} campaign(s), "
              f"{out['violations_total']} violation(s)")
    return 0 if out["ok"] else 1


def _draw_main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos draw",
        description="print the cocktails N campaigns would arm (pure: "
                    "nothing runs; diffable determinism check)")
    _add_campaign_flags(ap)
    args = ap.parse_args(argv)

    import numpy as np

    from .core import chaos

    ops = sorted({chaos.MIX_TO_OP[m.strip()]
                  for m in args.mix.split(",") if m.strip()})
    for i in range(args.campaigns):
        rng = np.random.default_rng([args.seed, i])
        plan = chaos.draw_cocktail(rng, args.backend, ops, args.replicas)
        problems = chaos.validate_cocktail(plan, args.backend)
        if problems:
            print(f"chaos draw: campaign {i} drew a matrix violation: "
                  f"{problems}", file=sys.stderr)
            return 1
        print(f"{i}\t{plan}")
    return 0


def _replay_main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos replay",
        description="re-run banked fixtures; pass iff observed "
                    "violations match each fixture's expectation")
    ap.add_argument("fixtures", nargs="*",
                    help="fixture JSON paths (default: every fixture "
                    "under tests/chaos_fixtures/)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    from .core import chaos

    paths = args.fixtures or sorted(
        glob.glob(os.path.join(chaos.fixtures_dir(), "*.json")))
    if not paths:
        print("chaos replay: no fixtures found", file=sys.stderr)
        return 2
    docs = []
    ok = True
    for path in paths:
        result, expected, observed = chaos.replay_fixture(path)
        match = expected == observed
        ok = ok and match
        docs.append({"fixture": os.path.basename(path),
                     "expected": expected, "observed": observed,
                     "match": match,
                     "cocktail": result.cocktail})
        if not args.as_json:
            mark = "ok  " if match else "FAIL"
            print(f"[{mark}] {os.path.basename(path)}: expected "
                  f"{expected or ['<none>']}, observed "
                  f"{observed or ['<none>']}")
    if args.as_json:
        print(json.dumps({"fixtures": docs, "ok": ok}, indent=2))
    return 0 if ok else 1


def _matrix_main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos matrix",
        description="print the clause-compatibility matrix")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    from .core import chaos

    if args.as_json:
        print(json.dumps({k: {
            "eligible": r.eligible, "backends": list(r.backends),
            "max_per_cocktail": r.max_per_cocktail,
            "conflicts": list(r.conflicts), "reason": r.reason,
        } for k, r in chaos.MATRIX.items()}, indent=2))
        return 0
    for kind, r in chaos.MATRIX.items():
        if r.eligible:
            extra = f", conflicts {'/'.join(r.conflicts)}" \
                if r.conflicts else ""
            print(f"{kind:<13} drawable on {'/'.join(r.backends)} "
                  f"(max {r.max_per_cocktail}{extra})")
        else:
            print(f"{kind:<13} excluded")
        print(f"{'':<13} {r.reason}")
    return 0


def main(argv: list[str]) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m cme213_tpu chaos "
              "<run|draw|replay|matrix> [args...]\n\n"
              "subcommands:\n"
              "  run     seeded campaigns: arm a drawn fault cocktail "
              "against a live serving\n"
              "          run, check global invariants, shrink + bank "
              "violations\n"
              "  draw    print the cocktails a run would arm "
              "(determinism check; pure)\n"
              "  replay  re-run banked fixtures, compare observed vs "
              "expected violations\n"
              "  matrix  print the clause-compatibility matrix")
        return 0 if argv else 2
    sub = {"run": _run_main, "draw": _draw_main, "replay": _replay_main,
           "matrix": _matrix_main}.get(argv[0])
    if sub is None:
        print(f"chaos: unknown subcommand {argv[0]!r} "
              f"(try run | draw | replay | matrix)", file=sys.stderr)
        return 2
    return sub(argv[1:])


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
