from .params import SimParams, GridMethod

__all__ = ["SimParams", "GridMethod"]
