"""Iterate on the Pallas stencil kernels on the real TPU chip.

Correctness at small size vs the XLA path, then timing at 4000^2 over
tile/k choices.  Dev tool, not part of the package.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))  # repo root
import time

import jax
import jax.numpy as jnp
import numpy as np

from cme213_tpu.config import SimParams
from cme213_tpu.grid import make_initial_grid
from cme213_tpu.ops import run_heat
from cme213_tpu.ops.stencil_pallas import run_heat_multistep, run_heat_pallas

dev = jax.devices()[0]
print("device:", dev)

# ---- correctness, 256^2 order 8 ----
p = SimParams(nx=256, ny=256, order=8, iters=8)
u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
ref = np.asarray(run_heat(jnp.array(u0), 8, p.order, p.xcfl, p.ycfl))

for name, fn in {
    "pallas t=64": lambda u: run_heat_pallas(u, 8, p.order, p.xcfl, p.ycfl,
                                             tile_y=64),
    "k4 t=64": lambda u: run_heat_multistep(u, 8, p.order, p.xcfl, p.ycfl,
                                            p.bc, k=4, tile_y=64),
    "k8 t=64": lambda u: run_heat_multistep(u, 8, p.order, p.xcfl, p.ycfl,
                                            p.bc, k=8, tile_y=64),
}.items():
    try:
        out = np.asarray(fn(jnp.array(u0)))
        err = np.abs(out - ref).max()
        print(f"{name}: max|err| = {err:.3e}", "OK" if err < 1e-5 else "BAD")
    except Exception as e:
        print(f"{name}: FAILED {type(e).__name__}: {e}")

if "--no-time" in sys.argv:
    sys.exit(0)

# ---- timing, 4000^2 order 8 ----
p = SimParams(nx=4000, ny=4000, order=8, iters=1000)
u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
iters = 200
bytes_per_iter = 2 * 4 * 4000 * 4000

cands = {"xla": lambda u, it: run_heat(u, it, p.order, p.xcfl, p.ycfl)}
for t in (80, 160, 200, 400):
    cands[f"pallas t={t}"] = (
        lambda u, it, t=t: run_heat_pallas(u, it, p.order, p.xcfl, p.ycfl,
                                           tile_y=t))
for k in (2, 4, 8):
    for t in (80, 160, 200):
        cands[f"k{k} t={t}"] = (
            lambda u, it, k=k, t=t: run_heat_multistep(
                u, it, p.order, p.xcfl, p.ycfl, p.bc, k=k, tile_y=t))

for name, fn in cands.items():
    try:
        jax.block_until_ready(fn(jax.device_put(u0), 8))
        u = jax.device_put(u0)
        t0 = time.perf_counter()
        jax.block_until_ready(fn(u, iters))
        dt = (time.perf_counter() - t0) / iters
        print(f"{name}: {dt * 1e3:.3f} ms/iter, "
              f"{bytes_per_iter / dt / 1e9:.1f} GB/s eff")
    except Exception as e:
        msg = str(e).split("\n")[0][:160]
        print(f"{name}: FAILED {type(e).__name__}: {msg}")
