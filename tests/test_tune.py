"""The autotuner (core/tune.py): gate-then-time search, persistent
winner cache, dispatch consumption, and the kill-switch.

The load-bearing contracts:

- a candidate whose conformance probe is poisoned (``wrong:<op>`` fault)
  is excluded BEFORE timing and can never win;
- winners persist across processes (``CME213_TUNE_CACHE``) and a fresh
  process's dispatch resolves statics from the cache — observable as a
  ``tune-hit`` event — without a single retrace;
- ``CME213_TUNE=0`` restores every built-in default without touching
  the cache;
- exact ties break deterministically to the first-registered candidate
  (scripted clock, so the tie is exact by construction).
"""

import json
import os
import subprocess
import sys

import pytest

from cme213_tpu.core import conformance, faults, metrics, trace, tune

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    monkeypatch.delenv(tune.CACHE_ENV, raising=False)
    monkeypatch.delenv(tune.KILL_ENV, raising=False)
    monkeypatch.delenv("CME213_CONFORMANCE_CACHE", raising=False)
    trace.flush_sink()
    trace.clear_events()
    metrics.reset()
    tune.reset()
    conformance.reset()
    yield
    trace.flush_sink()
    trace.clear_events()
    metrics.reset()
    tune.reset()
    conformance.reset()


# ---------------------------------------------------------- cache unit

def test_store_lookup_resolve_roundtrip():
    tune.store("toy", "n64", "float32", statics={"block": 8},
               candidate="b8", ms=1.0, gbs=2.0)
    rec = tune.lookup("toy", "n64")
    assert rec["statics"] == {"block": 8} and rec["candidate"] == "b8"
    # resolve() is restricted to declared defaults: stale statics a call
    # site doesn't understand can never leak in
    out = tune.resolve("toy", "n64", "float32", block=1, other=0)
    assert out == {"block": 8, "other": 0}
    events = [e for e in trace.events() if e["event"] == "tune-hit"]
    assert events and json.loads(events[0]["statics"]) == {"block": 8}


def test_resolve_default_when_empty():
    out = tune.resolve("toy", "n64", "float32", block=4)
    assert out == {"block": 4}
    assert any(e["event"] == "tune-default" for e in trace.events())


def test_disk_cache_persist_and_reload(tmp_path, monkeypatch):
    path = tmp_path / "tune.json"
    monkeypatch.setenv(tune.CACHE_ENV, str(path))
    tune.store("toy", "n64", "float32", statics={"block": 8},
               candidate="b8", ms=1.0, gbs=2.0)
    assert path.exists()
    tune.reset()   # drop in-process state: the next lookup re-reads disk
    assert tune.lookup("toy", "n64")["statics"] == {"block": 8}


def test_corrupt_disk_cache_serves_defaults(tmp_path, monkeypatch):
    path = tmp_path / "tune.json"
    path.write_text("{not json")
    monkeypatch.setenv(tune.CACHE_ENV, str(path))
    assert tune.lookup("toy", "n64") is None


def test_clear_removes_disk_and_memory(tmp_path, monkeypatch):
    path = tmp_path / "tune.json"
    monkeypatch.setenv(tune.CACHE_ENV, str(path))
    tune.store("toy", "n64", "float32", statics={}, candidate="x",
               ms=1.0, gbs=0.0)
    assert tune.clear() == 1
    assert not path.exists()
    assert tune.lookup("toy", "n64") is None


# ---------------------------------------------------------- kill-switch

def test_kill_switch_restores_defaults(monkeypatch):
    from cme213_tpu.ops import segmented

    tune.store("segmented_scan", "crossover", "float32",
               statics={"threshold": 123}, candidate="thr123",
               ms=1.0, gbs=1.0)
    assert segmented.scan_threshold() == 123
    monkeypatch.setenv(tune.KILL_ENV, "0")
    assert segmented.scan_threshold() == segmented.BLOCKED_SCAN_THRESHOLD
    assert tune.lookup("segmented_scan", "crossover") is None
    assert tune.resolve("toy", "n64", "float32", block=4) == {"block": 4}
    # flipping the switch back re-enables the same cached winner
    monkeypatch.setenv(tune.KILL_ENV, "1")
    assert segmented.scan_threshold() == 123


# ------------------------------------------------ gate-then-time search

def _toy_candidate(label, statics=None, gate=None, runner=None):
    runner = runner or (lambda: None)
    return tune.Candidate(label, statics if statics is not None
                          else {"which": label}, lambda: runner, gate)


class ScriptClock:
    """now() advances a fixed quantum per call: every candidate measures
    the identical duration, so ties are exact by construction."""

    def __init__(self, step_s: float = 0.001):
        self.t = 0.0
        self.step = step_s

    def now(self) -> float:
        self.t += self.step
        return self.t

    def sleep(self, seconds: float) -> None:
        self.t += seconds


def test_tie_breaks_to_first_registered():
    clock = ScriptClock()
    space = tune.TuneSpace("toy", "sc", "float32",
                           (_toy_candidate("a"), _toy_candidate("b")))
    rep = tune.run_space(space, clock=clock, runs=3, persist=False)
    assert rep["winner"]["candidate"] == "a"
    # same measurements, reversed registration order: the OTHER one wins
    # — proof the tie-break is registration order, not timing noise
    space_r = tune.TuneSpace("toy", "sc", "float32",
                             (_toy_candidate("b"), _toy_candidate("a")))
    rep_r = tune.run_space(space_r, clock=ScriptClock(), runs=3,
                           persist=False)
    assert rep_r["winner"]["candidate"] == "b"


def test_gated_out_candidate_cannot_win():
    clock = ScriptClock()
    space = tune.TuneSpace("toy", "sc", "float32", (
        _toy_candidate("bad", gate=lambda: False),
        _toy_candidate("good"),
    ))
    rep = tune.run_space(space, clock=clock, runs=2, persist=False)
    assert rep["winner"]["candidate"] == "good"
    bad = [t for t in rep["trials"] if t["candidate"] == "bad"]
    assert bad and not bad[0]["ok"]
    assert metrics.counter("tune.rejected").value == 1


def test_dying_probe_is_a_veto_not_a_crash():
    def boom():
        raise RuntimeError("probe died")

    space = tune.TuneSpace("toy", "sc", "float32", (
        _toy_candidate("bad", gate=boom),
        _toy_candidate("good"),
    ))
    rep = tune.run_space(space, clock=ScriptClock(), runs=2, persist=False)
    assert rep["winner"]["candidate"] == "good"


def test_no_survivor_raises_tune_error():
    space = tune.TuneSpace("toy", "sc", "float32",
                           (_toy_candidate("bad", gate=lambda: False),))
    with pytest.raises(tune.TuneError):
        tune.run_space(space, clock=ScriptClock(), runs=1, persist=False)


def test_wrong_fault_candidate_is_excluded_before_timing():
    """A ``wrong:spmv_scan``-poisoned conformance probe must exclude
    exactly the first gated candidate — it never reaches timing and can
    never win, however fast it would have measured."""
    with faults.injected("wrong:spmv_scan"):
        conformance.reset()   # no cached verdict may mask the fault
        rep = tune.run("spmv_scan", n=2048, iters=2, runs=2, persist=False,
                       block_sizes=(512, 1024))
    # flat is the ungated reference; the first gated candidate is
    # blocked/bs512, whose probe the fault perturbed
    bad = [t for t in rep["trials"] if t["candidate"] == "blocked/bs512"]
    assert bad and not bad[0]["ok"]
    assert rep["winner"]["candidate"] != "blocked/bs512"
    # the OTHER blocked candidate's probe ran clean and was timed
    ok_labels = {t["candidate"] for t in rep["trials"] if t["ok"]}
    assert "flat" in ok_labels and "blocked/bs1024" in ok_labels


def test_winner_event_and_persist(tmp_path, monkeypatch):
    path = tmp_path / "tune.json"
    monkeypatch.setenv(tune.CACHE_ENV, str(path))
    space = tune.TuneSpace("toy", "sc", "float32",
                           (_toy_candidate("a", statics={"block": 2}),))
    tune.run_space(space, clock=ScriptClock(), runs=2)
    events = trace.events()
    winners = [e for e in events if e["event"] == "tune-winner"]
    assert winners and winners[0]["candidate"] == "a"
    trials = [e for e in events if e["event"] == "tune-trial"]
    assert trials and trials[0]["ok"]
    data = json.loads(path.read_text())
    (key,) = data.keys()
    assert key.endswith("|toy|sc|float32")
    assert data[key]["statics"] == {"block": 2}


# ------------------------------------------- dispatch consumes winners

def test_spmv_dispatch_resolves_tuned_kernel():
    from cme213_tpu.apps import spmv_scan as sp
    from cme213_tpu.core import programs

    prob = sp.generate_problem(256, p=8, q=128, iters=2, seed=0)
    bucket = f"n{programs.canonical_size(prob.n)}"
    tune.store("spmv_scan", bucket, "float32",
               statics={"kernel": "blocked", "block_size": 128},
               candidate="blocked/bs128", ms=1.0, gbs=1.0)
    out = sp.run_spmv_scan(prob, kernel="auto")
    errs = sp.external_check(prob, out)
    assert errs["rel_l2"] < 1e-4
    hits = [e for e in trace.events() if e["event"] == "tune-hit"]
    assert any(e["op"] == "spmv_scan" and e["shape_class"] == bucket
               for e in hits)


def test_heat_dispatch_pins_explicit_tiles_over_tuned(monkeypatch):
    """An explicitly passed tile knob must win over a cached entry —
    only caller-open knobs resolve from the tuner."""
    import numpy as np

    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops.stencil_pipeline import run_heat_resilient

    p = SimParams(nx=32, ny=32, order=2, iters=2)
    u0 = make_initial_grid(p)
    # the grid carries its halo: nx=ny=32 at order 2 is a 34x34 array
    tune.store("heat", "34x34/order2/k1", "float32",
               statics={"tile_y": 8, "tile_x": 32},
               candidate="pipeline/ty8/tx32", ms=1.0, gbs=1.0)
    res = run_heat_resilient(u0, 2, 2, p.xcfl, p.ycfl, p.bc,
                             tile_y=16, interpret=True)
    assert np.isfinite(np.asarray(res.value)).all()
    # tile_y was pinned by the caller; only tile_x was open to the tuner
    hits = [e for e in trace.events() if e["event"] == "tune-hit"]
    assert hits and json.loads(hits[0]["statics"]) == {"tile_x": 32}


def test_serve_batch_cap_consults_cache():
    from cme213_tpu.serve.server import tuned_batch_cap

    tune.store("serve.spmv_scan", "n64/i2", "float32",
               statics={"max_batch": 2}, candidate="b2", ms=1.0, gbs=0.0)
    assert tuned_batch_cap("spmv_scan", "n64/i2", 8) == 2
    # the tuned width is a cap, never an escalation past the server's
    assert tuned_batch_cap("spmv_scan", "n64/i2", 1) == 1
    assert tuned_batch_cap("spmv_scan", "other", 8) == 8


def test_sort_auto_dispatches_tuned_kernel():
    import numpy as np

    import jax.numpy as jnp

    from cme213_tpu.core import programs
    from cme213_tpu.ops.sort import sort_auto

    keys_host = np.random.default_rng(0).integers(
        0, 2 ** 32, 512, dtype=np.uint32)
    bucket = f"n{programs.canonical_size(512)}"
    tune.store("sort", bucket, "uint32", statics={"kernel": "bitonic"},
               candidate="bitonic", ms=1.0, gbs=1.0)
    out = np.asarray(sort_auto(jnp.asarray(keys_host)))
    assert (out == np.sort(keys_host)).all()


# ----------------------------------------- cross-process acceptance run

@pytest.mark.slow
def test_subprocess_round_trip_zero_retraces(tmp_path):
    """The acceptance path end-to-end: ``tune run`` in one process
    persists a winner; a FRESH process's ``run_spmv_scan`` resolves its
    statics from the disk cache (``tune-hit`` in its trace) with zero
    retraces."""
    cache = tmp_path / "tune.json"
    trace_file = tmp_path / "trace.jsonl"
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "CME213_TUNE_CACHE": str(cache)}
    env.pop("CME213_TRACE_FILE", None)
    r = subprocess.run(
        [sys.executable, "-m", "cme213_tpu", "tune", "run",
         "--op", "spmv_scan", "--n", "4096", "--iters", "2",
         "--runs", "2", "--json"],
        env=env, cwd=REPO_ROOT, timeout=600, capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    (rep,) = json.loads(r.stdout)
    assert rep["winner"]["candidate"]
    data = json.loads(cache.read_text())
    assert any("|spmv_scan|n4096|" in k for k in data)

    script = (
        "from cme213_tpu.apps import spmv_scan as sp\n"
        "from cme213_tpu.core import trace\n"
        "prob = sp.generate_problem(4096, p=64, q=2048, iters=2, seed=0)\n"
        "sp.run_spmv_scan(prob, kernel='auto')\n"
        "trace.flush_sink()\n")
    env2 = {**env, "CME213_TRACE_FILE": str(trace_file)}
    r2 = subprocess.run([sys.executable, "-c", script], env=env2,
                        cwd=REPO_ROOT, timeout=600, capture_output=True,
                        text=True)
    assert r2.returncode == 0, r2.stderr
    events = [json.loads(line) for line in
              trace_file.read_text().splitlines() if line.strip()]
    hits = [e for e in events
            if e.get("event") == "tune-hit" and e.get("op") == "spmv_scan"]
    assert hits, "fresh process never consulted the tuning cache"
    assert not [e for e in events if e.get("event") == "compile-retrace"]


# --------------------------------------------------------------- sweeps

def test_sort_sweep_carries_tuned_column():
    from cme213_tpu.bench import sweeps

    tune.store("sort", "n4096", "uint32", statics={"kernel": "bitonic"},
               candidate="bitonic", ms=1.0, gbs=1.0)
    rows = sweeps.sort_sweep(ns=(4096,), kernels=("lax", "auto"))
    assert all(r["tuned"] == "bitonic" for r in rows)
    assert all(r["ok"] for r in rows)
    assert {r["kernel"] for r in rows} == {"lax", "auto"}


def test_spmv_sweep_carries_tuned_column():
    from cme213_tpu.bench import sweeps

    tune.store("spmv_scan", "n4096", "float32",
               statics={"kernel": "flat"}, candidate="flat",
               ms=1.0, gbs=1.0)
    rows = sweeps.spmv_scan_sweep(ns=(4096,), iters=2, kernels=("flat",))
    assert rows and rows[0]["tuned"] == "flat"


# ------------------------------------------------------------- trace CLI

def test_trace_summary_tuning_section(tmp_path, monkeypatch, capsys):
    from cme213_tpu import trace_cli

    path = tmp_path / "t.jsonl"
    monkeypatch.setenv(trace.TRACE_FILE_ENV, str(path))
    space = tune.TuneSpace("toy", "sc", "float32",
                           (_toy_candidate("a", statics={"block": 2}),))
    tune.run_space(space, clock=ScriptClock(), runs=2, persist=False)
    tune.store("toy", "sc", "float32", statics={"block": 2},
               candidate="a", ms=1.0, gbs=0.0)
    tune.resolve("toy", "sc", "float32", block=4)
    trace.flush_sink()
    monkeypatch.delenv(trace.TRACE_FILE_ENV)
    capsys.readouterr()
    assert trace_cli.main(["summary", str(path)]) == 0
    out = capsys.readouterr().out
    assert "tuning:" in out
    assert "1 winner(s)" in out
    assert "toy [sc]" in out
    agg = json.loads(
        subprocess.run([sys.executable, "-m", "cme213_tpu", "trace",
                        "summary", "--json", str(path)],
                       cwd=REPO_ROOT, env={**os.environ,
                                           "JAX_PLATFORMS": "cpu"},
                       capture_output=True, text=True, timeout=300).stdout)
    assert agg["tuning"]["hits"] == 1


# ------------------------------------------------------- bench retries

def test_bench_retry_policy_backoff_is_deterministic():
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)
    from cme213_tpu.core.resilience import FailureKind, RetryPolicy

    sleeps = []
    policy = RetryPolicy(max_retries=1, base_delay_s=120.0, multiplier=1.0,
                         max_delay_s=120.0, retry_on=(FailureKind.RUNTIME,),
                         sleep=sleeps.append)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise bench.DeviceUnreachable("preflight device unreachable")
        return {"ok": True}

    assert policy.run(flaky, op="bench.heat2d") == {"ok": True}
    assert sleeps == [120.0]           # deterministic, injectable backoff
    retries = [e for e in trace.events() if e["event"] == "retry"]
    assert retries and retries[0]["op"] == "bench.heat2d"


def test_bench_device_unreachable_classifies_runtime():
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)
    from cme213_tpu.core.resilience import FailureKind, classify_failure

    kind = classify_failure(bench.DeviceUnreachable("device unreachable"))
    assert kind == FailureKind.RUNTIME
