"""Simulation config: ``params.in``-compatible parsing + CFL/timestep derivation.

Re-expresses the reference's ``simParams`` class
(``hw/hw2/programming/2dHeat.cu:90-228``) as a frozen dataclass with derived
fields.  The distributed variant adds ``grid_method`` (1-D stripes vs 2-D
blocks) and ``synchronous`` (sync vs comm/compute-overlap), matching the hw5
``simParams`` (``hw/hw5/programming/2dHeat.cpp:53-177``, parse at ``:127-135``).

File formats (whitespace-separated, like the reference's ``ifs >>`` parse):

  hw2 (single device, ``hw/hw2/programming/2dHeat.cu:172-178``)::

      nx ny
      lx ly
      alpha
      iters
      order
      ic
      bc_top bc_left bc_bottom bc_right

  hw5 (distributed) inserts ``grid_method`` and ``sync`` between ``ic`` and
  ``bc`` (``hw/hw5/programming/2dHeat.cpp:127-135``).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class GridMethod(enum.IntEnum):
    """Domain-decomposition selector (hw5 ``gridMethod_``): 1 = 1-D stripes,
    2 = 2-D blocks (``hw/hw5/programming/2dHeat.cpp:284-377``)."""

    STRIPES_1D = 1
    BLOCKS_2D = 2


_BORDER_FOR_ORDER = {2: 1, 4: 2, 8: 4}


@dataclass(frozen=True)
class SimParams:
    nx: int = 10
    ny: int = 10
    lx: float = 1.0
    ly: float = 1.0
    alpha: float = 1.0
    iters: int = 1000
    order: int = 2
    ic: float = 5.0
    # boundary conditions: 0 top, then counter-clockwise (top, left, bottom,
    # right) — reference ``bc[4]`` comment, ``hw/hw2/programming/2dHeat.cu:128``
    bc_top: float = 0.0
    bc_left: float = 10.0
    bc_bottom: float = 0.0
    bc_right: float = 10.0
    # distributed-only knobs (hw5)
    grid_method: GridMethod = GridMethod.STRIPES_1D
    synchronous: bool = True

    # derived (filled in __post_init__)
    dx: float = field(init=False)
    dy: float = field(init=False)
    dt: float = field(init=False)
    xcfl: float = field(init=False)
    ycfl: float = field(init=False)
    border_size: int = field(init=False)
    gx: int = field(init=False)
    gy: int = field(init=False)

    def __post_init__(self):
        if self.order not in _BORDER_FOR_ORDER:
            raise ValueError(f"Unsupported discretization order {self.order}")
        dx = self.lx / (self.nx - 1)
        dy = self.ly / (self.ny - 1)
        dt, xcfl, ycfl = _calc_dt_cfl(self.order, self.alpha, dx, dy)
        border = _BORDER_FOR_ORDER[self.order]
        object.__setattr__(self, "dx", dx)
        object.__setattr__(self, "dy", dy)
        object.__setattr__(self, "dt", dt)
        object.__setattr__(self, "xcfl", xcfl)
        object.__setattr__(self, "ycfl", ycfl)
        object.__setattr__(self, "border_size", border)
        object.__setattr__(self, "gx", self.nx + 2 * border)
        object.__setattr__(self, "gy", self.ny + 2 * border)

    @classmethod
    def from_file(cls, path: str, distributed: bool = False) -> "SimParams":
        with open(path) as f:
            tok = f.read().split()
        it = iter(tok)
        nx, ny = int(next(it)), int(next(it))
        lx, ly = float(next(it)), float(next(it))
        alpha = float(next(it))
        iters = int(next(it))
        order = int(next(it))
        ic = float(next(it))
        if distributed:
            grid_method = GridMethod(int(next(it)))
            synchronous = bool(int(next(it)))
        else:
            grid_method = GridMethod.STRIPES_1D
            synchronous = True
        bc = [float(next(it)) for _ in range(4)]
        return cls(
            nx=nx, ny=ny, lx=lx, ly=ly, alpha=alpha, iters=iters, order=order,
            ic=ic, bc_top=bc[0], bc_left=bc[1], bc_bottom=bc[2], bc_right=bc[3],
            grid_method=grid_method, synchronous=synchronous,
        )

    def to_file(self, path: str, distributed: bool = False) -> None:
        parts = [
            f"{self.nx} {self.ny}",
            f"{self.lx} {self.ly}",
            f"{self.alpha}",
            f"{self.iters}",
            f"{self.order}",
            f"{self.ic}",
        ]
        if distributed:
            parts.append(f"{int(self.grid_method)}")
            parts.append(f"{int(self.synchronous)}")
        parts.append(
            f"{self.bc_top} {self.bc_left} {self.bc_bottom} {self.bc_right}"
        )
        with open(path, "w") as f:
            f.write("\n".join(parts) + "\n")

    @property
    def bc(self) -> tuple[float, float, float, float]:
        """(top, left, bottom, right)."""
        return (self.bc_top, self.bc_left, self.bc_bottom, self.bc_right)

    def describe(self) -> str:
        """Verbose config echo (reference ``2dHeat.cu:199-202``)."""
        return (
            f"nx: {self.nx} ny: {self.ny}\ngx: {self.gx} gy: {self.gy}\n"
            f"lx {self.lx}: ly: {self.ly}\nalpha: {self.alpha}\n"
            f"iterations: {self.iters}\norder: {self.order}\nic: {self.ic}\n"
            f"dx: {self.dx} dy: {self.dy}\n"
            f"dt: {self.dt} xcfl: {self.xcfl} ycfl: {self.ycfl}"
        )


def _calc_dt_cfl(order: int, alpha: float, dx: float, dy: float):
    """CFL-stable timestep + per-axis CFL numbers.

    Same derivation as the reference's ``simParams::calcDtCFL``
    (``hw/hw2/programming/2dHeat.cu:206-228``): come in just under the 0.5
    stability limit, scale by the order's leading finite-difference
    denominator (1 / 12 / 5040) with center-coefficient factor (2 / 16·2 /
    8064·2 ... expressed exactly as the reference writes it).
    """
    dx2, dy2 = dx * dx, dy * dy
    margin = 0.5 - 0.0001
    if order == 2:
        dt = margin * (dx2 * dy2) / (alpha * (dx2 + dy2))
        return dt, alpha * dt / dx2, alpha * dt / dy2
    if order == 4:
        dt = margin * (12 * dx2 * dy2) / (16 * alpha * (dx2 + dy2))
        return dt, alpha * dt / (12 * dx2), alpha * dt / (12 * dy2)
    if order == 8:
        dt = margin * (5040 * dx2 * dy2) / (8064 * alpha * (dx2 + dy2))
        return dt, alpha * dt / (5040 * dx2), alpha * dt / (5040 * dy2)
    raise ValueError(f"Unsupported discretization order {order}")
