"""Fleet telemetry (ISSUE 13): cross-process trace propagation, the live
collector, federated metrics, and the ``collect``/``top`` consoles.

The load-bearing assertions: one trace id demonstrably spans processes —
a supervised 2-rank gang (launcher + both workers + the post-restart
incarnation) and a plain-launch child both stamp the launcher's id on
every record — and the collector reconstructs the fleet view LIVE from
per-rank sinks (rotation/truncation/partial-line tolerant), with the
federated Prometheus exposition rendering the same per-rank state.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from cme213_tpu.core import faults, metrics, trace
from cme213_tpu.core.collector import (Collector, SinkTailer,
                                       write_fleet_exposition)
from cme213_tpu import top_cli, trace_cli
from cme213_tpu.core import collector as collector_cli

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_slate():
    trace.flush_sink()
    trace.clear_events()
    metrics.reset()
    yield
    trace.flush_sink()
    trace.clear_events()
    metrics.reset()
    faults.reset()


# ----------------------------------------------------- context propagation

def test_trace_id_minted_once_and_stable():
    a = trace.trace_id()
    assert a and a == trace.trace_id()
    rec = trace.record_event("heartbeat", rank=0, step=1)
    assert rec["trace"] == a


def test_inherited_context_overrides_local_id(monkeypatch):
    monkeypatch.setenv(trace.TRACE_CONTEXT_ENV, json.dumps(
        {"trace_id": "T1", "parent_span_id": "P9"}))
    assert trace.trace_id() == "T1"
    assert trace.inherited_parent_id() == "P9"
    assert trace.record_event("heartbeat", rank=0, step=1)["trace"] == "T1"
    # a root span parents under the spawning process's open span; nested
    # spans parent locally as usual
    with trace.span("root"):
        with trace.span("inner"):
            pass
    begins = trace.events("span-begin")
    root_b = next(b for b in begins if b["span"] == "root")
    inner_b = next(b for b in begins if b["span"] == "inner")
    assert root_b["parent"] == "P9"
    assert inner_b["parent"] == root_b["id"]


def test_malformed_context_falls_back_to_local(monkeypatch):
    monkeypatch.setenv(trace.TRACE_CONTEXT_ENV, "{not json")
    tid = trace.trace_id()   # must not raise
    assert tid and tid == trace.trace_id()
    assert trace.inherited_parent_id() is None


def test_propagation_env_round_trip(monkeypatch):
    monkeypatch.delenv(trace.TRACE_CONTEXT_ENV, raising=False)
    with trace.span("launching"):
        env = trace.propagation_env()
        ctx = json.loads(env[trace.TRACE_CONTEXT_ENV])
        assert ctx["trace_id"] == trace.trace_id()
        assert ctx["parent_span_id"] == trace.current_span_id()
    # outside any span, an inherited parent is forwarded unchanged
    monkeypatch.setenv(trace.TRACE_CONTEXT_ENV, json.dumps(
        {"trace_id": "T1", "parent_span_id": "P9"}))
    ctx = json.loads(trace.propagation_env()[trace.TRACE_CONTEXT_ENV])
    assert ctx == {"trace_id": "T1", "parent_span_id": "P9"}


def test_subprocess_child_joins_the_trace(monkeypatch):
    code = ("from cme213_tpu.core import trace; "
            "print('TID', trace.trace_id(), trace.inherited_parent_id())")
    monkeypatch.setenv("PYTHONPATH", _REPO)
    with trace.span("spawn"):
        env = dict(os.environ, **trace.propagation_env())
        parent = trace.current_span_id()
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert out.stdout.split() == ["TID", trace.trace_id(), parent]


# ----------------------------------------------------- {rank} templating

def test_format_trace_path_units():
    assert trace.format_trace_path("t-{rank}.jsonl", 3) == "t-3.jsonl"
    assert trace.format_trace_path("t-{rank}.jsonl", None) == "t-main.jsonl"
    assert trace.format_trace_path("t-{rank}.jsonl", "") == "t-main.jsonl"
    assert trace.format_trace_path("flat.jsonl", None) == "flat.jsonl"


def test_rank_placeholder_never_reaches_open(tmp_path, monkeypatch):
    """The env template must resolve even without the launcher — unset,
    EMPTY, and numeric JAX_PROCESS_ID all yield concrete filenames."""
    monkeypatch.setenv(trace.TRACE_FILE_ENV, str(tmp_path / "t-{rank}.jsonl"))
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    trace.record_event("heartbeat", rank=0, step=1)
    monkeypatch.setenv("JAX_PROCESS_ID", "")   # set-but-empty edge
    trace.record_event("heartbeat", rank=0, step=2)
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    trace.record_event("heartbeat", rank=3, step=3)
    trace.flush_sink()
    names = sorted(p.name for p in tmp_path.iterdir())
    assert names == ["t-3.jsonl", "t-main.jsonl"]
    assert not any("{rank}" in n for n in names)
    assert len((tmp_path / "t-main.jsonl").read_text().splitlines()) == 2


# ------------------------------------------------------------- the tailer

def _line(step, t=1.0, rank=0):
    return json.dumps({"event": "heartbeat", "t": t, "rank": rank,
                       "step": step, "pid": 1, "incarnation": 0,
                       "trace": "T1"}) + "\n"


def test_tailer_partial_lines_buffered(tmp_path):
    p = tmp_path / "s.jsonl"
    tailer = SinkTailer(str(p))
    assert tailer.poll() == []                       # not yet created
    full, torn = _line(1), _line(2, t=2.0)
    p.write_text(full + torn[:10])                   # torn mid-record
    assert [r["step"] for r in tailer.poll()] == [1]
    with open(p, "a") as f:
        f.write(torn[10:])                           # the rest arrives
    assert [r["step"] for r in tailer.poll()] == [2]
    assert tailer.malformed == 0


def test_tailer_survives_rotation(tmp_path):
    p = tmp_path / "s.jsonl"
    p.write_text(_line(1) + _line(2, t=2.0))
    tailer = SinkTailer(str(p))
    assert len(tailer.poll()) == 2
    fresh = tmp_path / "s.jsonl.new"                 # new inode
    fresh.write_text(_line(7, t=3.0))
    os.replace(fresh, p)
    assert [r["step"] for r in tailer.poll()] == [7]


def test_tailer_survives_truncation(tmp_path):
    p = tmp_path / "s.jsonl"
    p.write_text(_line(1) + _line(2, t=2.0))
    tailer = SinkTailer(str(p))
    assert len(tailer.poll()) == 2
    p.write_text(_line(9, t=3.0))                    # shrunk in place
    assert [r["step"] for r in tailer.poll()] == [9]


def test_tailer_counts_malformed_lines(tmp_path):
    p = tmp_path / "s.jsonl"
    p.write_text("not json\n" + json.dumps({"no_event": 1}) + "\n"
                 + _line(4))
    tailer = SinkTailer(str(p))
    assert [r["step"] for r in tailer.poll()] == [4]
    assert tailer.malformed == 2


# ---------------------------------------------------------- the collector

def _fleet_fixture(tmp_path):
    """Synthetic launcher + 2-rank sinks shaped like a rankkill run."""
    launcher = [
        {"event": "gang-launch", "t": 0.0, "rank": None, "incarnation": 0,
         "world": 2, "coordinator": "c:1", "pid": 9, "trace": "T1"},
        {"event": "rank-failed", "t": 3.0, "rank": 1, "incarnation": 0,
         "reason": "exit", "code": 113, "pid": 9, "trace": "T1"},
        {"event": "gang-restart", "t": 3.1, "rank": None, "incarnation": 1,
         "reason": "exit", "pid": 9, "trace": "T1"},
        {"event": "gang-launch", "t": 3.2, "rank": None, "incarnation": 1,
         "world": 2, "coordinator": "c:2", "pid": 9, "trace": "T1"},
        {"event": "gang-exit", "t": 9.0, "rank": None, "incarnation": 1,
         "rc": 0, "pid": 9, "trace": "T1"},
    ]
    r0 = [
        {"event": "heartbeat", "t": 1.0, "rank": 0, "step": 2, "pid": 10,
         "incarnation": 0, "trace": "T1"},
        {"event": "epoch-commit", "t": 2.0, "rank": 0, "epoch": 1,
         "step": 2, "world": 2, "shards": 2, "ms": 5.0, "pid": 10,
         "incarnation": 0, "trace": "T1"},
        {"event": "span-begin", "t": 4.0, "rank": 0, "span": "solve",
         "id": "a.1", "parent": None, "pid": 12, "incarnation": 1,
         "trace": "T1"},
        {"event": "span-end", "t": 6.0, "rank": 0, "span": "solve",
         "id": "a.1", "parent": None, "ms": 2000.0, "pid": 12,
         "incarnation": 1, "trace": "T1"},
        {"event": "metrics-snapshot", "t": 8.0, "rank": 0,
         "metrics": {"counters": {"fleet.steps": 6}, "gauges": {},
                     "histograms": {}},
         "pid": 12, "incarnation": 1, "trace": "T1"},
    ]
    r1 = [
        {"event": "heartbeat", "t": 1.1, "rank": 1, "step": 1, "pid": 11,
         "incarnation": 0, "trace": "T1"},
        {"event": "heartbeat", "t": 7.0, "rank": 1, "step": 5, "pid": 13,
         "incarnation": 1, "trace": "T1"},
    ]
    paths = []
    for name, recs in (("f-main.jsonl", launcher), ("f-0.jsonl", r0),
                       ("f-1.jsonl", r1)):
        p = tmp_path / name
        p.write_text("".join(json.dumps(r) + "\n" for r in recs))
        paths.append(str(p))
    return paths


def test_collector_merges_and_aggregates(tmp_path):
    paths = _fleet_fixture(tmp_path)
    coll = Collector([str(tmp_path / "f-*.jsonl")])  # glob form
    batch = coll.poll()
    assert [r["t"] for r in batch] == sorted(r["t"] for r in batch)
    st = coll.state()
    assert st["trace_ids"] == ["T1"]
    assert list(st["ranks"]) == ["r0", "r1", "main"]
    assert st["fleet"] == {"exits": 1, "launches": 2, "restarts": 1,
                           "verdicts": 1, "commits": 1}
    assert st["verdicts"] == [{"rank": 1, "reason": "exit",
                               "incarnation": 0, "t": 3.0}]
    # rank-failed comes from the LAUNCHER: r1's pid stays the worker's,
    # and the incarnation-1 heartbeat clears the failed state
    r1 = st["ranks"]["r1"]
    assert r1["pid"] == 13 and r1["state"] == "running" and r1["step"] == 5
    assert r1["incarnation"] == 1
    # ages are relative to the NEWEST observed event (t=9.0), not wall
    # clock — deterministic for --once --json
    assert r1["heartbeat_age_s"] == 2.0
    assert st["ranks"]["main"]["pid"] == 9 and st["last_rc"] == 0
    assert st["spans"]["solve"] == {"count": 1, "total_ms": 2000.0,
                                    "max_ms": 2000.0}
    assert st["commit_lag_s"] == 7.0
    assert coll.fleet_snapshots() == {
        "r0": {"counters": {"fleet.steps": 6}, "gauges": {},
               "histograms": {}}}
    # incremental: nothing new -> empty batch, state unchanged
    assert coll.poll() == [] and coll.state()["events"] == st["events"]


def test_collect_cli_once_json_and_text(tmp_path, capsys):
    paths = _fleet_fixture(tmp_path)
    assert collector_cli.main([*paths, "--once", "--json"]) == 0
    st = json.loads(capsys.readouterr().out)
    assert st["trace_ids"] == ["T1"] and set(st["ranks"]) == {
        "r0", "r1", "main"}
    assert collector_cli.main([*paths, "--once"]) == 0
    out = capsys.readouterr().out
    assert "3 proc(s)" in out and "1 trace id(s)" in out
    assert "verdict: rank 1 exit" in out


def test_collect_cli_follow_streams_jsonl(tmp_path, capsys):
    paths = _fleet_fixture(tmp_path)
    assert collector_cli.main(
        [*paths, "--follow", "--interval", "0.01",
         "--max-seconds", "0.05"]) == 0
    lines = capsys.readouterr().out.splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert len(recs) == 12 and all("_file" not in r for r in recs)
    assert [r["t"] for r in recs] == sorted(r["t"] for r in recs)


# ------------------------------------------------------------ top console

def test_top_once_json_and_text(tmp_path, capsys):
    paths = _fleet_fixture(tmp_path)
    assert top_cli.main([*paths, "--once", "--json"]) == 0
    st = json.loads(capsys.readouterr().out)
    assert st["fleet"]["restarts"] == 1 and "r1" in st["ranks"]
    assert top_cli.main([*paths, "--once"]) == 0
    out = capsys.readouterr().out
    assert "cme213 fleet" in out and "trace T1" in out
    assert "PROC" in out and "HB AGE" in out
    assert "restarts=1" in out and "solve" in out


def test_top_folds_supervisor_heartbeats(tmp_path, capsys):
    from cme213_tpu.dist.supervisor import HeartbeatWriter, \
        read_all_heartbeats

    HeartbeatWriter(str(tmp_path), rank=0).beat(4)
    HeartbeatWriter(str(tmp_path), rank=1).beat(9)
    assert {r: b["step"] for r, b in read_all_heartbeats(
        str(tmp_path)).items()} == {0: 4, 1: 9}
    sink = tmp_path / "s.jsonl"
    sink.write_text(_line(None, t=1.0, rank=0).replace('"step": null', '"x": 0'))
    assert top_cli.main([str(sink), "--once", "--json",
                         "--hb-dir", str(tmp_path)]) == 0
    st = json.loads(capsys.readouterr().out)
    assert st["heartbeats"]["1"]["step"] == 9
    assert st["ranks"]["r0"]["step"] == 4   # folded from the beat file


# ------------------------------------------------------- federated metrics

def test_merge_snapshots_folds_ranks():
    a = {"counters": {"c": 2, "only_a": 1}, "gauges": {"g": 1.0, "s": "x"},
         "histograms": {"h": {"count": 2, "sum": 3.0, "min": 1.0,
                              "max": 2.0, "mean": 1.5, "p50": 1.0,
                              "p90": 2.0, "p99": 2.0}}}
    b = {"counters": {"c": 3}, "gauges": {"g": 4.0},
         "histograms": {"h": {"count": 1, "sum": 9.0, "min": 9.0,
                              "max": 9.0, "mean": 9.0, "p50": 9.0,
                              "p90": 9.0, "p99": 9.0}}}
    m = metrics.merge_snapshots({"r0": a, "r1": b})
    assert m["counters"] == {"c": 5, "only_a": 1}
    assert m["gauges"] == {"g": 4.0}            # fleet max; strings dropped
    h = m["histograms"]["h"]
    assert h["count"] == 3 and h["sum"] == 12.0
    assert h["min"] == 1.0 and h["max"] == 9.0
    assert h["p50"] == 9.0                      # per-rank max upper bound
    assert h["mean"] == 4.0
    assert m["ranks"] == ["r0", "r1"]


def test_render_prometheus_fleet_labels_and_rollup():
    metrics.counter("serve.shed.queue-full").inc(2)
    metrics.gauge("depth").set(3)
    metrics.histogram("lat.ms").observe(4.0)
    snap = metrics.snapshot()
    metrics.reset()
    text = metrics.render_prometheus(fleet={"r0": snap, "r1": snap})
    assert "# HELP cme213_serve_shed_total" in text
    # unlabeled rollup first, then per-rank labeled series
    assert 'cme213_serve_shed_total{reason="queue-full"} 4' in text
    assert ('cme213_serve_shed_total{reason="queue-full",rank="r0"} 2'
            in text)
    assert 'cme213_depth{rank="r1"} 3' in text and "cme213_depth 3" in text
    assert 'cme213_lat_ms_bucket{le="4",rank="r0"} 1' in text
    assert "cme213_lat_ms_count 2" in text      # rollup sums counts
    assert 'cme213_lat_ms_count{rank="r1"} 1' in text


def test_write_fleet_exposition_pins_the_file(tmp_path, monkeypatch):
    dest = tmp_path / "fleet.prom"
    monkeypatch.setenv(metrics.METRICS_FILE_ENV, str(dest))
    sink = tmp_path / "s.jsonl"
    sink.write_text(json.dumps(
        {"event": "metrics-snapshot", "t": 1.0, "rank": 0,
         "metrics": {"counters": {"steps": 5}, "gauges": {},
                     "histograms": {}}}) + "\n")
    metrics.counter("launcher.polls").inc(7)
    assert write_fleet_exposition(
        [str(sink)], extra={"launcher": metrics.snapshot()}) == str(dest)
    text = dest.read_text()
    assert 'cme213_steps_total{rank="r0"} 5' in text
    assert 'cme213_launcher_polls_total{rank="launcher"} 7' in text
    # the atexit single-process writer must NOT clobber the fleet file
    metrics._emit_exit_snapshot()
    assert 'rank="r0"' in dest.read_text()


# --------------------------------------------------- serve trace stamping

class _Echo:
    op = "echo"

    def shape_class(self, payload, coarse=False):
        return "any"

    def rungs(self, degraded=False):
        return ("fast",)

    def run_batch(self, payloads, rung, coarse=False):
        return list(payloads)

    def preflight_builder(self, payloads, rung, coarse=False):
        return None


def test_server_stamps_trace_ids():
    from cme213_tpu.core.resilience import VirtualClock
    from cme213_tpu.serve import Server

    server = Server(adapters={"echo": _Echo()}, clock=VirtualClock())
    rid = server.submit("echo", 1)
    res = server.drain()[0]
    assert res.rid == rid and res.trace_id == trace.trace_id()
    assert trace.events("request-served")[-1]["trace"] == trace.trace_id()
    # an explicit id (remote caller) is carried end to end, sheds included
    res2 = server.submit("echo", 2, deadline_ms=0, trace_id="remote-7")
    assert res2.status == "shed" and res2.trace_id == "remote-7"
    assert trace.events("deadline-shed")[-1]["trace"] == "remote-7"


def test_loadgen_report_carries_trace_id():
    from cme213_tpu.serve.loadgen import slo_report

    snap = metrics.snapshot()
    report = slo_report({"results": [], "elapsed_s": 1.0}, snap, snap)
    assert report["trace_id"] == trace.trace_id()


# --------------------------------------------------------- CLI windowing

def _windowed_file(tmp_path):
    p = tmp_path / "w.jsonl"
    recs = [{"event": "heartbeat", "t": float(t), "rank": 0, "step": i,
             "pid": 1, "incarnation": 0, "trace": "T1"}
            for i, t in enumerate((100.0, 200.0, 300.0))]
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(p), recs


def test_window_events_units(tmp_path):
    _, recs = _windowed_file(tmp_path)
    assert [e["step"] for e in trace_cli.window_events(recs,
                                                      since="150000")] \
        == [1, 2]                       # 150s back from the newest (300)
    from datetime import datetime

    iso = datetime.fromtimestamp(200.0).isoformat()
    assert [e["step"] for e in trace_cli.window_events(recs, since=iso)] \
        == [1, 2]
    assert [e["step"] for e in trace_cli.window_events(recs, last=1)] == [2]
    assert trace_cli.window_events(recs, last=0) == []
    with pytest.raises(ValueError):
        trace_cli.window_events(recs, since="yesterday-ish")


def test_cli_since_last_and_single_trace(tmp_path, capsys):
    path, _ = _windowed_file(tmp_path)
    assert trace_cli.main(["timeline", path, "--last", "1"]) == 0
    out = capsys.readouterr().out
    assert out.count("heartbeat") == 1 and "step=2" in out
    assert trace_cli.main(["summary", path, "--since", "150000"]) == 0
    assert "2 events" in capsys.readouterr().out
    assert trace_cli.main(["summary", path, "--since", "garbage"]) == 2
    capsys.readouterr()
    # --single-trace: one id passes, a second id fails
    assert trace_cli.main(["summary", path, "--single-trace"]) == 0
    with open(path, "a") as f:
        f.write(json.dumps({"event": "heartbeat", "t": 400.0, "rank": 1,
                            "step": 9, "pid": 2, "incarnation": 0,
                            "trace": "T2"}) + "\n")
    assert trace_cli.main(["summary", path, "--single-trace"]) == 1
    assert "expected exactly one trace id" in capsys.readouterr().err


def test_cli_merge_follow_streams(tmp_path, capsys):
    paths = _fleet_fixture(tmp_path)
    assert trace_cli.main(
        ["merge", "--follow", *paths, "--interval", "0.01",
         "--max-seconds", "0.05"]) == 0
    recs = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()]
    assert len(recs) == 12 and all("_file" not in r for r in recs)
    assert trace_cli.main(
        ["merge", "--follow", "--timeline", *paths, "--interval", "0.01",
         "--max-seconds", "0.05"]) == 0
    out = capsys.readouterr().out
    assert "gang-launch" in out and "rank-failed" in out


def test_summary_reports_trace_ids_and_pids(tmp_path, capsys):
    paths = _fleet_fixture(tmp_path)
    import io

    agg = trace_cli.summarize(trace_cli.load_events(paths),
                              out=io.StringIO())
    assert agg["trace_ids"] == ["T1"]
    assert agg["pids"] == [9, 10, 11, 12, 13]


# ------------------------------------------------------------- end to end

_GANG_WORKER = textwrap.dedent("""
    import sys, time
    sys.path.insert(0, {repo!r})
    from cme213_tpu.core import faults, metrics, trace
    from cme213_tpu.dist.supervisor import heartbeat_from_env

    hb = heartbeat_from_env()
    metrics.counter("fleet.steps")        # arm the exit snapshot
    with trace.span("fleet.worker"):
        for step in range(6):
            hb.beat(step)
            faults.maybe_kill_rank(step)
            metrics.counter("fleet.steps").inc()
            time.sleep(0.05)
""")


def test_supervised_gang_shares_one_trace_id(tmp_path, monkeypatch, capsys):
    """The acceptance path: launcher + both ranks + the post-restart
    incarnation all stamp ONE trace id; worker root spans parent under
    the launcher's gang-launch span; the collector and the federated
    exposition reconstruct the same fleet."""
    from cme213_tpu.dist.launch import launch_supervised

    worker = tmp_path / "worker.py"
    worker.write_text(_GANG_WORKER.format(repo=_REPO))
    monkeypatch.setenv(trace.TRACE_FILE_ENV,
                       str(tmp_path / "gang-{rank}.jsonl"))
    monkeypatch.setenv(metrics.METRICS_FILE_ENV,
                       str(tmp_path / "fleet.prom"))
    monkeypatch.setenv("CME213_FAULTS", "rankkill:1:2")
    rc = launch_supervised(2, [sys.executable, str(worker)],
                           stall_timeout=60, max_restarts=1, timeout=240)
    out = capsys.readouterr().out
    assert rc == 0, out
    trace.flush_sink()

    files = sorted(tmp_path.glob("gang-*.jsonl"))
    assert [f.name for f in files] == ["gang-0.jsonl", "gang-1.jsonl",
                                       "gang-main.jsonl"]
    recs = [json.loads(ln) for f in files
            for ln in f.read_text().splitlines()]
    ids = {r.get("trace") for r in recs}
    assert ids == {trace.trace_id()}, ids          # ONE id, this process's
    pids = {r["pid"] for r in recs}
    assert len(pids) >= 4                          # launcher + 2x2 workers
    assert {r["incarnation"] for r in recs} >= {0, 1}

    # causal parenting: every worker root span hangs off a gang-launch
    gang_spans = {r["id"] for r in recs
                  if r["event"] == "span-begin" and r["span"] == "gang-launch"}
    worker_roots = [r for r in recs if r["event"] == "span-begin"
                    and r["span"] == "fleet.worker"]
    assert len(gang_spans) == 2 and len(worker_roots) >= 3
    assert all(r["parent"] in gang_spans for r in worker_roots)

    coll = Collector([str(tmp_path / "gang-*.jsonl")])
    coll.poll()
    st = coll.state()
    assert st["fleet"]["launches"] == 2 and st["fleet"]["restarts"] == 1
    assert st["verdicts"][0]["rank"] == 1
    assert st["ranks"]["r0"]["state"] == "running"
    assert st["ranks"]["r1"]["incarnation"] == 1

    # the merged stream passes the CI gate form
    capsys.readouterr()
    assert trace_cli.main(
        ["summary", *[str(f) for f in files], "--single-trace",
         "--require", "gang-launch,heartbeat"]) == 0

    # federated exposition: both ranks labeled, launcher rolled in
    prom = (tmp_path / "fleet.prom").read_text()
    assert 'rank="r0"' in prom and 'rank="r1"' in prom
    assert "# HELP" in prom


def test_plain_launch_propagates_context(tmp_path, monkeypatch, capsys):
    """The loadgen-shaped path: a plain (unsupervised) launch child
    inherits the launcher's trace id, and the launcher records the
    gang-launch/gang-exit lifecycle."""
    from cme213_tpu.dist.launch import launch

    code = ("from cme213_tpu.core import trace; "
            "print('CHILD', trace.trace_id())")
    monkeypatch.setenv("PYTHONPATH", _REPO)
    rc = launch(1, [sys.executable, "-c", code], timeout=120)
    out = capsys.readouterr().out
    assert rc == 0, out
    assert f"CHILD {trace.trace_id()}" in out
    assert trace.events("gang-launch")[-1]["world"] == 1
    assert trace.events("gang-exit")[-1]["rc"] == 0
