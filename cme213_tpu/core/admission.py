"""Memory-aware admission control — preflight before dispatch.

The reference assumed its problems fit: a too-large grid died inside a
CUDA allocation with whatever error the driver felt like printing.  On a
TPU the equivalent is an HBM ``RESOURCE_EXHAUSTED`` mid-solve — after
minutes of useful work, with the donated input buffers already gone.
This module moves that discovery to *before* dispatch, and gives the
solvers a graceful response when it happens anyway:

- :func:`memory_budget` — the per-device byte budget:
  ``CME213_MEMORY_BUDGET`` (plain bytes or ``K``/``M``/``G`` suffix) when
  set, else the detected device memory (``memory_stats()['bytes_limit']``
  — absent on the CPU backend, where admission is env-opt-in).
- :func:`preflight` — lower + compile a jitted computation and read its
  ``memory_analysis()`` (arguments + outputs + temps − donated aliases);
  an over-budget program is **rejected** with a structured
  ``admission-rejected`` event instead of being launched to die.
- :func:`admit_chunk` — the degradation loop: halve a size knob (solve
  chunk length, pipeline tile) until its preflight fits, emitting a
  ``chunk-shrunk`` event per halving; only a floor-size program that
  still cannot fit raises :class:`AdmissionError`.

The *reactive* half lives next to the solvers: ``classify_failure``
buckets runtime ``RESOURCE_EXHAUSTED`` into ``FailureKind.RESOURCE`` and
the checkpointed/supervised drivers respond by halving their chunk and
retrying from the last durable state (``core/checkpoint.py``,
``dist/heat.py``, ``apps/spmv_scan.py``).  ``oom:<op>`` fault clauses
(``core/faults.py``) raise a synthetic ``RESOURCE_EXHAUSTED`` so every
response path is testable on CPU.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from . import metrics
from .errors import FrameworkError
from .trace import record_event

#: per-device memory budget override, bytes (suffixes K/M/G accepted)
BUDGET_ENV = "CME213_MEMORY_BUDGET"

_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}


class AdmissionError(FrameworkError):
    """The computation cannot fit the memory budget at any allowed size."""


def parse_budget(raw: str) -> int:
    """``"1073741824"`` / ``"512M"`` / ``"16g"`` -> bytes."""
    raw = raw.strip().lower()
    mult = 1
    if raw and raw[-1] in _SUFFIX:
        mult = _SUFFIX[raw[-1]]
        raw = raw[:-1]
    return int(float(raw) * mult)


def memory_budget() -> int | None:
    """The effective per-device byte budget, or None (admission off).

    ``CME213_MEMORY_BUDGET`` wins; otherwise the first device's reported
    ``bytes_limit`` (TPU/GPU — the CPU backend reports nothing, so CPU
    runs only do admission when the env is set, which is also how tests
    fake a budget).
    """
    raw = os.environ.get(BUDGET_ENV)
    if raw and raw.strip():
        try:
            return parse_budget(raw)
        except ValueError:
            return None
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 — detection must never break dispatch
        pass
    return None


@dataclass(frozen=True)
class Decision:
    admitted: bool
    required_bytes: int | None   # None when memory analysis is unavailable
    budget_bytes: int | None
    detail: str


def estimate_bytes(compiled) -> int | None:
    """Peak-footprint estimate from a compiled computation's
    ``memory_analysis()``: arguments + outputs + temps − donated aliases.
    None when the backend exposes no analysis (pass-open)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # noqa: BLE001
        return None
    if ma is None:
        return None
    try:
        return int(ma.argument_size_in_bytes + ma.output_size_in_bytes
                   + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
    except (AttributeError, TypeError):
        return None


def preflight(fn, *args, op: str = "preflight", budget: int | None = None,
              **kwargs) -> Decision:
    """Admission decision for ``fn(*args, **kwargs)`` (``fn`` jitted).

    Lowers and compiles the program (the jit cache serves the real call
    afterwards) and compares its analyzed footprint to ``budget``
    (default :func:`memory_budget`).  No budget, or no analysis from the
    backend, admits pass-open — admission control must never turn a
    healthy program away on missing information.  A rejection emits
    ``admission-rejected`` and bumps ``admission.rejected``.
    """
    budget = memory_budget() if budget is None else budget
    if budget is None:
        return Decision(True, None, None, "no budget: admission off")
    try:
        compiled = fn.lower(*args, **kwargs).compile()
    except Exception as e:  # noqa: BLE001 — compile failures belong to the
        # fallback ladder, not admission; surface them there
        return Decision(True, None, budget,
                        f"preflight compile failed ({type(e).__name__}): "
                        f"pass-open")
    required = estimate_bytes(compiled)
    if required is None:
        return Decision(True, None, budget, "no memory analysis: pass-open")
    if required > budget:
        metrics.counter("admission.rejected").inc()
        record_event("admission-rejected", op=op, requested_bytes=required,
                     budget_bytes=budget,
                     detail=f"footprint {required} > budget {budget}")
        return Decision(False, required, budget,
                        f"footprint {required} > budget {budget}")
    metrics.counter("admission.admitted").inc()
    return Decision(True, required, budget,
                    f"footprint {required} <= budget {budget}")


def admit_chunk(op: str, initial: int, preflight_at, floor: int = 1,
                halve=None) -> int:
    """Largest admitted size knob, halving down from ``initial``.

    ``preflight_at(size) -> Decision`` runs the admission check at a
    candidate size (build the jitted program for that chunk length / tile
    height and :func:`preflight` it).  Each rejection emits a
    ``chunk-shrunk`` event and halves (``halve(size)`` when given — e.g.
    tile quantization — else integer halving).  A ``floor``-size program
    that is still over budget raises :class:`AdmissionError`: the budget
    says it can never fit, and a structured refusal beats an opaque
    device OOM after minutes of work.
    """
    size = initial
    while True:
        decision = preflight_at(size)
        if decision.admitted:
            return size
        if size <= floor:
            raise AdmissionError(
                f"{op}: floor size {size} still over budget "
                f"({decision.detail})")
        smaller = max(floor, halve(size) if halve is not None else size // 2)
        if smaller >= size:
            raise AdmissionError(
                f"{op}: cannot shrink below {size} ({decision.detail})")
        metrics.counter("admission.chunk_shrunk").inc()
        record_event("chunk-shrunk", op=op, from_size=size, to_size=smaller,
                     reason="admission-preflight")
        size = smaller


def admit_batch(op: str, requested: int, preflight_at,
                floor: int = 1) -> int:
    """Batch-size admission for the serving layer: the largest batch size
    (≤ ``requested``) whose stacked/vmapped program preflights within the
    budget — the :func:`admit_chunk` halving loop with the size knob
    meaning "requests per device program".  Requests beyond the admitted
    size stay queued for the next batch rather than being refused: unlike
    a solve chunk, a batch can always shrink to 1 without changing any
    request's result (each lane is an independent solve), so only a
    single-request program over budget raises :class:`AdmissionError`.

    Serving preflights are cached by the caller per (op, shape-class,
    size) — the jit cache already makes repeat lowers cheap, but the
    scheduler shouldn't even reach Python dispatch per batch.
    """
    return admit_chunk(op, requested, preflight_at, floor=floor)
