#!/bin/bash
# Probe the TPU tunnel until it answers; record status + timestamp.
# Writes /tmp/tpu_status: "UP <epoch>" once a trivial device op completes,
# otherwise keeps appending DOWN probes to /tmp/tpu_probe.log.
# Used while the tunnel is wedged so bench capture can start the moment it
# recovers (round-1 failure mode: BENCH_r01 = 0.0, device unreachable).
INTERVAL="${1:-120}"
DEADLINE="${2:-14400}"   # give up after 4h by default
start=$(date +%s)
while true; do
  now=$(date +%s)
  if [ $((now - start)) -gt "$DEADLINE" ]; then
    echo "GAVE_UP $now" > /tmp/tpu_status
    exit 1
  fi
  if timeout 90 python -c "
import jax, jax.numpy as jnp
assert jax.devices()[0].platform != 'cpu', 'silent CPU fallback'
(jnp.ones((8,8))*2).block_until_ready()
" >/dev/null 2>&1; then
    echo "UP $(date +%s)" > /tmp/tpu_status
    echo "$(date -Is) UP" >> /tmp/tpu_probe.log
    exit 0
  fi
  echo "$(date -Is) DOWN" >> /tmp/tpu_probe.log
  echo "DOWN $(date +%s)" > /tmp/tpu_status
  sleep "$INTERVAL"
done
