"""Multi-tenant serving front end over the solver stack.

The paper's solvers assume one caller with one problem; this package
puts a front door on them that stays up under many callers: a bounded
request queue, shape-class batching (same-class solves vmapped into one
device program), per-request deadlines, memory-budget admission, a
per-(op, rung) circuit breaker over the fallback ladders, and graceful
degradation under pressure — every refusal structured, every mode shift
visible in ``trace summary``.  See ``docs/serving.md``.

Beyond the single in-process server, ``transport.py`` adds a concurrent
socket front end (length-prefixed JSON frames; the caller-driven
``step()`` loop becomes one of two drive modes), and ``router.py`` +
``fleet.py`` replicate the server across supervised worker processes
with tenant-fair routing and SLO-burn autoscaling (``python -m
cme213_tpu fleet up``).
"""

from .request import (  # noqa: F401
    ADMISSION,
    DEADLINE,
    FAILED,
    OK,
    PHASES,
    QUEUE_FULL,
    SHED,
    RequestSpec,
    SolveRequest,
    SolveResult,
)
from .server import BoundedQueue, Server, tuned_batch_cap  # noqa: F401
from .slo import Objective, SLOMonitor  # noqa: F401
from .workloads import ADAPTERS, CipherRequest  # noqa: F401

# socket transport / replicated fleet (imported lazily by consumers to
# keep `import cme213_tpu.serve` light: no sockets, no subprocess)
__all__ = [
    "ADAPTERS", "ADMISSION", "BoundedQueue", "CipherRequest", "DEADLINE",
    "FAILED", "OK", "Objective", "PHASES", "QUEUE_FULL", "RequestSpec",
    "SHED", "SLOMonitor", "Server", "SolveRequest", "SolveResult",
    "tuned_batch_cap",
]


def main(argv: list[str]) -> int:
    """``python -m cme213_tpu serve <subcommand>`` dispatcher."""
    import sys

    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m cme213_tpu serve <loadgen|warmup> "
              "[args...]\n\n"
              "subcommands:\n"
              "  loadgen   drive the server with synthetic load and print "
              "an SLO report\n"
              "  warmup    pre-compile the canonical serving buckets "
              "(with CME213_COMPILE_CACHE set, into the persistent disk "
              "cache for warm process starts)\n\n"
              "loadgen --transport HOST:PORT drives a socket front end "
              "(see `python -m cme213_tpu fleet`) with real concurrent "
              "client threads")
        return 0 if argv else 2
    if argv[0] == "loadgen":
        from . import loadgen

        return loadgen.main(argv[1:])
    if argv[0] == "warmup":
        from . import warmup

        return warmup.main(argv[1:])
    print(f"serve: unknown subcommand {argv[0]!r} (try loadgen | warmup)",
          file=sys.stderr)
    return 2
