"""Profiling/tracing hooks.

The reference's tracing is labeled phase timers around every stage plus
offline derived metrics (SURVEY §5).  ``PhaseTimer`` covers that; this module
adds the device-level profile the CUDA events couldn't give: a context
manager around ``jax.profiler`` producing an XPlane trace (viewable in
TensorBoard/Perfetto) for kernel-level overlap verification — which SURVEY §7
calls out as the way "async" overlap must be verified on TPU.
"""

from __future__ import annotations

from contextlib import contextmanager


@contextmanager
def device_trace(log_dir: str):
    """Capture a device profile of the enclosed block into ``log_dir``."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
