"""Front-tier request routing: tenant-fair dispatch, replica health,
SLO-burn autoscaling.

The router is the policy half of the replicated fleet
(``serve/fleet.py`` is the mechanism half: processes, sockets,
threads).  Everything here is **pure and synchronous** — no threads, no
sockets, all timing on the injectable ``core.resilience.Clock`` — so
the scheduling and scaling decisions are unit-testable exactly like the
batching server's.  The fleet front end serializes access with one
lock and feeds the router events (submissions, completions, transport
failures, replica deaths); the router answers with assignments.

- **Tenant-weighted fair dispatch**: one backlog queue per tenant and a
  deficit-round-robin scan (Shreedhar & Varghese) — each visit grants a
  tenant ``quantum x weight`` credit, a dispatch costs 1.  A noisy
  tenant with a thousand queued requests cannot starve a quiet one: the
  scan interleaves tenants every round, so the quiet tenant's p99 is
  bounded by the fleet's batch time, not the noisy backlog.  This is
  the serving analog of the reference's Torque/qsub queue: submission
  order does not equal dispatch order; the scheduler owns placement.
- **Replica selection**: among replicas that are up, have spare
  dispatch capacity, and whose per-replica circuit breaker
  (``core.resilience.CircuitBreaker``, op ``fleet.route``, rung
  ``r<rank>``) admits traffic, pick the least-loaded.  A replica that
  fails transport repeatedly trips its breaker and is routed around
  until the cooldown's half-open probe readmits it.
- **Zero-loss ledger**: every assignment is tracked in an in-flight
  table until completion.  A dead replica's in-flight tickets are
  requeued at the *front* of their tenant queues (``request-requeued``
  events) — an accepted request is never lost, merely re-dispatched
  (solves are pure, so a double execution is harmless and the first
  response wins).
- **Autoscaling** (:class:`Autoscaler`): consumes the SLO monitor's
  two-window burn signal (``serve/slo.py``).  Sustained burn spawns a
  replica (``scale-up``); sustained health at low occupancy retires one
  (``scale-down``).  Both directions have a sustain window *and* a
  shared action cooldown — hysteresis on the injectable clock, so the
  fleet cannot flap.
"""

from __future__ import annotations

import itertools
from collections import Counter, deque
from dataclasses import dataclass, field

from ..core import metrics
from ..core.resilience import CircuitBreaker, Clock
from ..core.trace import begin_span, record_event

#: breaker identity for replica routing failures
ROUTE_OP = "fleet.route"


def _rung(rank: int) -> str:
    return f"r{rank}"


@dataclass
class Ticket:
    """One accepted front-tier request, from submit to response."""

    seq: int
    op: str
    tenant: str
    doc: dict                      # opaque wire doc, forwarded verbatim
    replica: int | None = None     # current assignment (None = queued)
    attempts: int = 0
    requeues: int = 0
    done: object = None            # threading.Event, set by the fleet
    result: dict | None = None
    # v2 transport extras: binary payload sections ride beside the doc
    # (forwarded to the replica untouched), and a pipelined client's
    # reply handle (connection, wire request id) replaces the Event
    sections: list = field(default_factory=list)
    reply: object = None
    # request-hop spans (core.trace.OpenSpan): ``hop`` covers the whole
    # front-tier residency (parented under the client's wire-carried
    # span), ``dispatch_hop`` one assignment attempt, ``wait_hop`` a
    # requeue detour; ``hop_ms`` collects closed-hop durations for the
    # response's waterfall breakdown
    hop: object = None
    dispatch_hop: object = None
    wait_hop: object = None
    hop_ms: dict = field(default_factory=dict)


@dataclass
class ReplicaState:
    rank: int
    capacity: int
    incarnation: int = 0
    up: bool = False
    retiring: bool = False
    inflight: int = 0
    routed: int = 0


@dataclass
class Autoscaler:
    """SLO-burn-driven fleet sizing with hysteresis; see module doc."""

    clock: Clock = field(default_factory=Clock)
    min_replicas: int = 1
    max_replicas: int = 4
    burn_sustain_s: float = 3.0    # burn must persist this long to grow
    ok_sustain_s: float = 6.0      # health+idle must persist to shrink
    low_occupancy: float = 0.5     # shrink only below this utilization
    cooldown_s: float = 10.0       # min spacing between actions

    _burn_since: float | None = field(default=None, repr=False)
    _ok_since: float | None = field(default=None, repr=False)
    _last_action: float | None = field(default=None, repr=False)

    def _cooled(self, now: float) -> bool:
        return (self._last_action is None
                or now - self._last_action >= self.cooldown_s)

    def evaluate(self, burning: bool, occupancy: float,
                 replicas: int) -> str | None:
        """One policy tick: ``"up"``, ``"down"``, or None.  Emits the
        ``scale-up`` / ``scale-down`` event at decision time; the fleet
        acts on the return value."""
        now = self.clock.now()
        if burning:
            self._ok_since = None
            if self._burn_since is None:
                self._burn_since = now
            if (now - self._burn_since >= self.burn_sustain_s
                    and self._cooled(now)
                    and replicas < self.max_replicas):
                self._burn_since = None
                self._last_action = now
                metrics.counter("fleet.scale_up").inc()
                record_event("scale-up", replicas=replicas + 1,
                             reason="slo-burn")
                return "up"
            return None
        self._burn_since = None
        if occupancy > self.low_occupancy:
            self._ok_since = None
            return None
        if self._ok_since is None:
            self._ok_since = now
        if (now - self._ok_since >= self.ok_sustain_s
                and self._cooled(now)
                and replicas > self.min_replicas):
            self._ok_since = None
            self._last_action = now
            metrics.counter("fleet.scale_down").inc()
            record_event("scale-down", replicas=replicas - 1,
                         reason="slo-ok")
            return "down"
        return None


class Router:
    """Tenant-fair, breaker-guarded dispatch over a replica set.

    Not thread-safe by design — the fleet front end owns one lock (a
    condition variable) around every call, which keeps this class
    deterministic enough to unit-test without processes or sockets.
    """

    def __init__(self, clock: Clock | None = None, quantum: float = 1.0,
                 weights: dict[str, float] | None = None,
                 capacity: int = 256, breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 5.0):
        self.clock = clock if clock is not None else Clock()
        self.quantum = quantum
        self.weights = dict(weights or {})
        self.capacity = capacity
        self.breaker = CircuitBreaker(threshold=breaker_threshold,
                                      cooldown_s=breaker_cooldown_s,
                                      clock=self.clock)
        self.replicas: dict[int, ReplicaState] = {}
        self._backlogs: dict[str, deque[Ticket]] = {}
        self._deficit: dict[str, float] = {}
        self._tenant_order: list[str] = []
        self._scan = 0                     # DRR rotation cursor
        self._seq = itertools.count()
        self._inflight: dict[int, Ticket] = {}
        self.requeues = Counter()          # per source replica
        self.total_requeues = 0

    # -------------------------------------------------------- replicas

    def register_replica(self, rank: int, capacity: int,
                         incarnation: int = 0) -> ReplicaState:
        rep = self.replicas.get(rank)
        if rep is None:
            rep = ReplicaState(rank, capacity)
            self.replicas[rank] = rep
        rep.capacity = capacity
        rep.incarnation = incarnation
        rep.up = True
        rep.retiring = False
        rep.inflight = 0
        return rep

    def mark_down(self, rank: int, reason: str = "exit") -> list[Ticket]:
        """Replica death: requeue every in-flight ticket it held (at the
        front of its tenant's backlog — it has already waited) and
        return them for observability."""
        rep = self.replicas.get(rank)
        if rep is not None:
            rep.up = False
            rep.inflight = 0
        lost = [t for t in self._inflight.values() if t.replica == rank]
        for t in lost:
            del self._inflight[t.seq]
            self._requeue(t, from_replica=rank)
        return lost

    def mark_retiring(self, rank: int) -> None:
        rep = self.replicas.get(rank)
        if rep is not None:
            rep.retiring = True

    def up_replicas(self) -> list[ReplicaState]:
        return [r for r in self.replicas.values() if r.up]

    def occupancy(self) -> float:
        cap = sum(r.capacity for r in self.up_replicas())
        if not cap:
            return 0.0
        return sum(r.inflight for r in self.up_replicas()) / cap

    # ---------------------------------------------------------- submit

    def submit(self, doc: dict) -> Ticket | None:
        """Accept into the tenant backlog, or refuse (None) when the
        front-tier backlog is at capacity — the same honest-refusal
        contract as the server's bounded queue."""
        backlog = sum(len(q) for q in self._backlogs.values())
        if backlog >= self.capacity:
            metrics.counter("fleet.shed.queue-full").inc()
            return None
        tenant = doc.get("tenant", "default")
        t = Ticket(seq=next(self._seq), op=doc.get("op", "?"),
                   tenant=tenant, doc=doc)
        # open the front-tier residency hop under the client's
        # wire-carried span, then rewrite the forwarded context so the
        # replica's hops parent under this one — the Dapper chain
        t.hop = begin_span("serve.hop.route",
                           parent=doc.get("parent_span"),
                           tail_key=f"t{t.seq}", head_key=t.seq,
                           **self._hop_tags(t))
        doc["parent_span"] = t.hop.id
        if tenant not in self._backlogs:
            self._backlogs[tenant] = deque()
            self._deficit[tenant] = 0.0
            self._tenant_order.append(tenant)
        self._backlogs[tenant].append(t)
        return t

    def _hop_tags(self, ticket: Ticket, **extra) -> dict:
        """Common tags for a ticket's hop spans; the request's own trace
        id (carried in the doc) overrides this process's, so every hop
        of one request lands on one trace id across the fleet."""
        tags = {"rid": ticket.seq, "op": ticket.op,
                "tenant": ticket.tenant, **extra}
        tid = ticket.doc.get("trace_id")
        if tid:
            tags["trace"] = tid
        return tags

    # -------------------------------------------------------- dispatch

    def _pick_replica(self) -> ReplicaState | None:
        ready = [r for r in self.up_replicas()
                 if not r.retiring and r.inflight < r.capacity
                 and self.breaker.allow(ROUTE_OP, _rung(r.rank))]
        if not ready:
            return None
        return min(ready, key=lambda r: (r.inflight, r.rank))

    def next_assignment(self) -> tuple[Ticket, int] | None:
        """Deficit-round-robin pick: the next (ticket, rank) to send, or
        None when the backlog is empty or no replica can take work."""
        if not any(self._backlogs.values()):
            return None
        rep = self._pick_replica()
        if rep is None:
            return None
        n = len(self._tenant_order)
        for i in range(n):
            tenant = self._tenant_order[(self._scan + i) % n]
            q = self._backlogs[tenant]
            if not q:
                self._deficit[tenant] = 0.0   # idle tenants bank nothing
                continue
            self._deficit[tenant] += self.quantum * self.weights.get(
                tenant, 1.0)
            if self._deficit[tenant] < 1.0:
                continue
            self._deficit[tenant] -= 1.0
            self._scan = (self._scan + i + 1) % n
            ticket = q.popleft()
            ticket.replica = rep.rank
            ticket.attempts += 1
            rep.inflight += 1
            rep.routed += 1
            self._inflight[ticket.seq] = ticket
            metrics.counter("fleet.routed").inc()
            if ticket.wait_hop is not None:    # the requeue detour ends
                ms = ticket.wait_hop.end(replica=rep.rank)
                if ms is not None:
                    ticket.hop_ms["requeue_ms"] = round(
                        ticket.hop_ms.get("requeue_ms", 0.0) + ms, 3)
                ticket.wait_hop = None
            if ticket.hop is not None:
                ticket.dispatch_hop = begin_span(
                    "serve.hop.dispatch", parent=ticket.hop.id,
                    tail_key=f"t{ticket.seq}", head_key=ticket.seq,
                    **self._hop_tags(ticket, replica=rep.rank))
            record_event("request-routed", rid=ticket.seq, op=ticket.op,
                         tenant=ticket.tenant, replica=rep.rank)
            return ticket, rep.rank
        return None

    # ------------------------------------------------------ completion

    def complete(self, ticket: Ticket, rank: int, ok: bool = True) -> bool:
        """A send finished (response received).  Returns False when the
        ticket had already been requeued elsewhere (stale completion
        after a mark_down race) — the caller should still deliver the
        response if the ticket is not done (first response wins)."""
        cur = self._inflight.get(ticket.seq)
        live = cur is not None and cur.replica == rank
        if live:
            del self._inflight[ticket.seq]
            rep = self.replicas.get(rank)
            if rep is not None and rep.inflight > 0:
                rep.inflight -= 1
            if ticket.dispatch_hop is not None:
                ms = ticket.dispatch_hop.end()
                if ms is not None:
                    ticket.hop_ms["dispatch_ms"] = ms
                ticket.dispatch_hop = None
        if ok:
            self.breaker.record_success(ROUTE_OP, _rung(rank))
        return live

    def fail_transport(self, ticket: Ticket, rank: int,
                       kind=None) -> None:
        """A send failed at the socket (replica dead or dying): trip the
        breaker a notch and requeue, unless mark_down beat us to it."""
        from ..core.resilience import FailureKind

        self.breaker.record_failure(ROUTE_OP, _rung(rank),
                                    kind or FailureKind.RUNTIME)
        cur = self._inflight.get(ticket.seq)
        if cur is None or cur.replica != rank:
            return
        del self._inflight[ticket.seq]
        rep = self.replicas.get(rank)
        if rep is not None and rep.inflight > 0:
            rep.inflight -= 1
        self._requeue(ticket, from_replica=rank)

    def _requeue(self, ticket: Ticket, from_replica: int) -> None:
        ticket.replica = None
        ticket.requeues += 1
        self.requeues[from_replica] += 1
        self.total_requeues += 1
        metrics.counter("fleet.requeued").inc()
        if ticket.dispatch_hop is not None:    # the attempt died partway
            ticket.dispatch_hop.end(requeued=True)
            ticket.dispatch_hop = None
        if ticket.hop is not None:
            ticket.wait_hop = begin_span(
                "serve.hop.requeue", parent=ticket.hop.id,
                tail_key=f"t{ticket.seq}", head_key=ticket.seq,
                **self._hop_tags(ticket, from_replica=from_replica))
        record_event("request-requeued", rid=ticket.seq, op=ticket.op,
                     tenant=ticket.tenant, from_replica=from_replica)
        q = self._backlogs.setdefault(ticket.tenant, deque())
        if ticket.tenant not in self._deficit:
            self._deficit[ticket.tenant] = 0.0
            self._tenant_order.append(ticket.tenant)
        q.appendleft(ticket)   # it already waited its turn

    # ----------------------------------------------------------- state

    def backlog(self) -> int:
        return sum(len(q) for q in self._backlogs.values())

    def inflight(self) -> int:
        return len(self._inflight)

    def state(self) -> dict:
        return {
            "backlog": self.backlog(),
            "inflight": self.inflight(),
            "occupancy": round(self.occupancy(), 4),
            "requeues": self.total_requeues,
            "replicas": {
                _rung(r.rank): {
                    "up": r.up,
                    "retiring": r.retiring,
                    "incarnation": r.incarnation,
                    "inflight": r.inflight,
                    "routed": r.routed,
                    "requeues": self.requeues.get(r.rank, 0),
                    "breaker": self.breaker.state(ROUTE_OP, _rung(r.rank)),
                }
                for r in self.replicas.values()
            },
        }
