"""Pipelined Pallas stencil (v2) vs the XLA path (interpret mode on CPU).

The pipeline kernel is the headline bench path; these tests pin its
bitwise equivalence to ``run_heat`` across orders, temporal-blocking
factors, awkward (non-128-lane, non-tile-divisible) shapes, and
non-uniform states — the ``hw2`` checker methodology (ULP compare,
``hw/hw2/programming/2dHeat.cu:651-671``) tightened to exact equality,
which holds because both paths accumulate taps in the same order.

Known limit of that contract (the ``FMA_XFAIL`` marks): at order 8 and
under temporal blocking (k>1) this jaxlib's XLA:CPU backend contracts
the y-axis tap accumulation and the final ``+ ycfl·accy`` combine into
FMAs on the concat-seam rows of the lowered roll/mask formulation, while
the shifted-slice formulation compiles to strict mul+add everywhere — a
deterministic 1-ULP divergence on boundary-adjacent rows.  Root-cause
note: docs/resilience.md, "Known divergence: FMA contraction".  The
conformance gate (``core/conformance.py``) probes exactly this contract
and keeps the diverging rungs out of the serving ladders, so these pins
stay as strict documentation of the kernel property rather than red CI.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cme213_tpu.config import SimParams
from cme213_tpu.grid import make_initial_grid
from cme213_tpu.ops import run_heat
from cme213_tpu.ops.stencil_pipeline import (
    pick_pipeline_tile,
    run_heat_pipeline,
)

INTERPRET = jax.devices()[0].platform != "tpu"

FMA_XFAIL = pytest.mark.xfail(
    strict=False,
    reason="1-ULP FMA-contraction divergence between XLA program "
           "formulations at order 8 / k>1 (docs/resilience.md 'Known "
           "divergence: FMA contraction'); the conformance gate demotes "
           "these rungs in serving paths")


def _run_both(p: SimParams, iters: int, k: int, tile_y: int):
    u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
    ref = np.asarray(run_heat(jnp.array(u0), iters, p.order, p.xcfl, p.ycfl))
    out = np.asarray(run_heat_pipeline(
        jnp.array(u0), iters, p.order, p.xcfl, p.ycfl, p.bc, k=k,
        tile_y=tile_y, interpret=INTERPRET))
    return ref, out


@pytest.mark.parametrize("order", [2, 4,
                                   pytest.param(8, marks=FMA_XFAIL)])
def test_bitwise_vs_xla(order):
    p = SimParams(nx=44, ny=40, order=order, iters=8)
    ref, out = _run_both(p, 8, k=1, tile_y=16)
    np.testing.assert_array_equal(out, ref)


@FMA_XFAIL
def test_non_pow2_tile_bitwise():
    """The VMEM clamp steps tiles down by halo quanta, so heights like 24
    or 184 (multiples of kpad, not powers of two) are now reachable —
    exercise one at each k parity."""
    p = SimParams(nx=100, ny=90, order=8, iters=8)
    ref, out = _run_both(p, 8, k=1, tile_y=24)
    np.testing.assert_array_equal(out, ref)
    ref, out = _run_both(p, 8, k=2, tile_y=24)
    np.testing.assert_array_equal(out, ref)


@FMA_XFAIL
@pytest.mark.parametrize("k,tile_y", [(2, 8), (4, 16), (8, 32)])
def test_temporal_blocking_bitwise(k, tile_y):
    p = SimParams(nx=44, ny=40, order=8, iters=8 * k)
    ref, out = _run_both(p, 8 * k, k=k, tile_y=tile_y)
    np.testing.assert_array_equal(out, ref)


@FMA_XFAIL
def test_awkward_shapes():
    # gx not lane-aligned, gy not tile-divisible, rectangular
    p = SimParams(nx=257, ny=121, order=4, iters=8)
    ref, out = _run_both(p, 8, k=4, tile_y=16)
    np.testing.assert_array_equal(out, ref)


@FMA_XFAIL
def test_nonuniform_state_and_bc():
    """Gradient interior + distinct BC values on all four sides."""
    p = SimParams(nx=40, ny=40, order=8, iters=4, bc_top=1.0,
                  bc_left=2.0, bc_bottom=3.0, bc_right=4.0)
    u0 = np.array(make_initial_grid(p, dtype=jnp.float32))
    b = p.border_size
    u0[b:-b, b:-b] += np.linspace(
        0, 1, p.ny * p.nx, dtype=np.float32).reshape(p.ny, p.nx)
    ref = np.asarray(run_heat(jnp.array(u0), 4, 8, p.xcfl, p.ycfl))
    out = np.asarray(run_heat_pipeline(
        jnp.array(u0), 4, 8, p.xcfl, p.ycfl, p.bc, k=2, tile_y=8,
        interpret=INTERPRET))
    np.testing.assert_array_equal(out, ref)


def test_pick_pipeline_tile():
    assert pick_pipeline_tile(4008, 1, 8) % 8 == 0
    assert pick_pipeline_tile(4008, 8, 8) % 32 == 0
    # always at least one halo quantum
    assert pick_pipeline_tile(16, 16, 8) >= 64


def test_pick_pipeline_tile_vmem_clamp():
    """With the grid width given, the double-buffered band footprint is
    clamped under VMEM_BUDGET_BYTES (the W=4096 x tile_y=256 remote-compile
    crash was 16.5 MiB against a ~16 MiB core)."""
    from cme213_tpu.ops.stencil_pipeline import (VMEM_BUDGET_BYTES,
                                                 _ceil_to)

    for k in (1, 2, 4, 8):
        kpad = _ceil_to(k * 4, 8)
        ty = pick_pipeline_tile(4008, k, 8, target=256, width=4008)
        assert ty % kpad == 0
        W = _ceil_to(4008, 128)
        assert 2 * 4 * W * (2 * ty + 2 * kpad) <= VMEM_BUDGET_BYTES
        assert ty < 256  # actually clamped at the headline width
    # narrow grids keep the requested target
    assert pick_pipeline_tile(264, 1, 8, target=256, width=264) == 256
    # no width → legacy behavior, no clamp
    assert pick_pipeline_tile(4008, 1, 8, target=256) == 256


@pytest.mark.parametrize("order", [2, pytest.param(8, marks=FMA_XFAIL)])
def test_roll_formulation_bitwise(order):
    """run_heat_roll (scatter-free full-grid XLA variant) vs run_heat."""
    from cme213_tpu.ops.stencil import run_heat_roll

    p = SimParams(nx=52, ny=44, order=order, iters=6, bc_top=1.5,
                  bc_left=0.5, bc_bottom=2.0, bc_right=0.25)
    u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
    ref = np.asarray(run_heat(jnp.array(u0), 6, order, p.xcfl, p.ycfl))
    out = np.asarray(run_heat_roll(jnp.array(u0), 6, order, p.xcfl,
                                   p.ycfl, p.bc))
    np.testing.assert_array_equal(out, ref)
    # k-unrolled temporal blocking: same sub-step chain, one loop body —
    # bitwise-equal for any k that divides iters
    for k in (2, 3, 6):
        out_k = np.asarray(run_heat_roll(jnp.array(u0), 6, order, p.xcfl,
                                         p.ycfl, p.bc, k=k))
        np.testing.assert_array_equal(out_k, ref)


@FMA_XFAIL
@pytest.mark.parametrize("k,tile_y,tile_x", [(1, 16, 128), (2, 8, 128),
                                             (4, 16, 256)])
def test_pipeline2d_bitwise(k, tile_y, tile_x):
    from cme213_tpu.ops.stencil_pipeline import run_heat_pipeline2d

    p = SimParams(nx=300, ny=120, order=8, iters=8 * k, bc_top=1.5,
                  bc_left=0.5, bc_bottom=2.0, bc_right=0.25)
    u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
    ref = np.asarray(run_heat(jnp.array(u0), 8 * k, 8, p.xcfl, p.ycfl))
    out = np.asarray(run_heat_pipeline2d(
        jnp.array(u0), 8 * k, 8, p.xcfl, p.ycfl, p.bc, k=k, tile_y=tile_y,
        tile_x=tile_x, interpret=INTERPRET))
    np.testing.assert_array_equal(out, ref)


def test_pipeline2d_single_tile_and_awkward():
    from cme213_tpu.ops.stencil_pipeline import run_heat_pipeline2d

    p = SimParams(nx=77, ny=33, order=2, iters=6)
    u0 = np.asarray(make_initial_grid(p, dtype=jnp.float32))
    ref = np.asarray(run_heat(jnp.array(u0), 6, 2, p.xcfl, p.ycfl))
    out = np.asarray(run_heat_pipeline2d(
        jnp.array(u0), 6, 2, p.xcfl, p.ycfl, p.bc, k=2, tile_y=8,
        tile_x=128, interpret=INTERPRET))
    np.testing.assert_array_equal(out, ref)
