"""Telemetry subsystem: spans, metrics registry, hardened sinks, event
schema, and the ``trace`` analysis CLI (ISSUE 4).

The schema test is the load-bearing one: it statically checks every
``record_event`` call site in the package against
``core/trace.EVENT_SCHEMA``, so a new event (or a renamed field) must be
registered before it can ship — the documented schema IS the wire format
``trace merge`` reconstructs gang timelines from.
"""

import ast
import json
import os
import pathlib

import pytest

import cme213_tpu
from cme213_tpu.core import metrics, trace
from cme213_tpu.core.timing import PhaseTimer
from cme213_tpu.core.trace import EVENT_SCHEMA, span, validate_record
from cme213_tpu import trace_cli


@pytest.fixture(autouse=True)
def _clean_telemetry():
    trace.flush_sink()
    trace.clear_events()
    yield
    trace.flush_sink()
    trace.clear_events()


# ------------------------------------------------------------------ spans

def test_span_nesting_parent_links_and_tags():
    with span("outer", kind="test"):
        with span("inner"):
            pass
    ev = trace.events()
    assert [e["event"] for e in ev] == [
        "span-begin", "span-begin", "span-end", "span-end"]
    outer_b, inner_b, inner_e, outer_e = ev
    assert outer_b["parent"] is None
    assert inner_b["parent"] == outer_b["id"]
    assert inner_e["id"] == inner_b["id"]
    assert outer_e["kind"] == "test" and outer_e["ms"] >= inner_e["ms"] >= 0


def test_span_ids_unique_and_stack_restored():
    ids = set()
    for _ in range(5):
        with span("s"):
            pass
    for e in trace.events("span-begin"):
        ids.add(e["id"])
    assert len(ids) == 5
    assert trace.current_span_id() is None


def test_span_error_tagged_and_reraised():
    with pytest.raises(ValueError):
        with span("boom"):
            raise ValueError("x")
    end = trace.events("span-end")[-1]
    assert end["error"] == "ValueError" and end["ms"] >= 0


def test_span_blocks_device_work():
    import jax.numpy as jnp

    with span("device") as sp:
        out = jnp.ones(128) * 2
        sp.block(out)
    assert trace.events("span-end")[-1]["ms"] >= 0


def test_span_durations_feed_metrics():
    metrics.reset()
    with span("timed"):
        pass
    snap = metrics.snapshot()
    assert snap["histograms"]["span.timed.ms"]["count"] == 1


def test_every_record_carries_process_tags(monkeypatch):
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    monkeypatch.setenv("CME213_INCARNATION", "1")
    rec = trace.record_event("heartbeat", rank=2, step=7)
    assert rec["pid"] == os.getpid()
    assert rec["rank"] == 2 and rec["incarnation"] == 1
    monkeypatch.delenv("JAX_PROCESS_ID")
    assert trace.record_event("heartbeat", rank=0, step=8)["rank"] == 0
    # auto tag is None for non-rank processes (explicit field wins above)
    assert trace.record_event("gang-exit", incarnation=0, rc=0)["rank"] is None


def test_phase_timer_emits_spans():
    t = PhaseTimer()
    with t.phase("phase-x") as ph:
        ph.block()  # no arrays: host-only phase
    assert t.ms("phase-x") >= 0
    ends = trace.events("span-end")
    assert [e["span"] for e in ends] == ["phase-x"]
    assert abs(ends[0]["ms"] - t.ms("phase-x")) < 50


# ------------------------------------------------------------------ buffer

def test_ring_buffer_cap(monkeypatch):
    monkeypatch.setenv(trace.TRACE_BUFFER_ENV, "4")
    trace.clear_events()  # re-reads the cap
    for i in range(10):
        trace.record_event("heartbeat", rank=0, step=i)
    ev = trace.events("heartbeat")
    assert len(ev) == 4 and [e["step"] for e in ev] == [6, 7, 8, 9]


def test_buffer_default_unbounded():
    for i in range(300):
        trace.record_event("heartbeat", rank=0, step=i)
    assert len(trace.events("heartbeat")) == 300


# ------------------------------------------------------------------- sinks

def test_sink_appends_jsonl_with_cached_handle(tmp_path, monkeypatch):
    path = tmp_path / "t.jsonl"
    monkeypatch.setenv(trace.TRACE_FILE_ENV, str(path))
    for i in range(3):
        trace.record_event("heartbeat", rank=0, step=i)
    trace.flush_sink()
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    assert [r["step"] for r in recs] == [0, 1, 2]
    assert all(not validate_record(r) for r in recs)
    # handle survives flush (reopened lazily) and keeps appending
    trace.record_event("heartbeat", rank=0, step=3)
    trace.flush_sink()
    assert len(path.read_text().splitlines()) == 4


def test_sink_rank_templating(tmp_path, monkeypatch):
    monkeypatch.setenv(trace.TRACE_FILE_ENV, str(tmp_path / "t-{rank}.jsonl"))
    monkeypatch.setenv("JAX_PROCESS_ID", "5")
    trace.record_event("heartbeat", rank=5, step=1)
    trace.flush_sink()
    assert (tmp_path / "t-5.jsonl").exists()
    monkeypatch.delenv("JAX_PROCESS_ID")
    trace.record_event("gang-launch", incarnation=0, world=2,
                       coordinator="x")
    trace.flush_sink()
    assert (tmp_path / "t-main.jsonl").exists()


def test_sink_broken_path_never_raises(monkeypatch):
    monkeypatch.setenv(trace.TRACE_FILE_ENV,
                       "/nonexistent-dir-xyz/t.jsonl")
    rec = trace.record_event("heartbeat", rank=0, step=1)  # must not raise
    assert rec["step"] == 1


def test_launcher_templates_trace_file_per_worker():
    from cme213_tpu.dist.launch import _template_trace_file

    env = {"CME213_TRACE_FILE": "/tmp/x/t-{rank}.jsonl"}
    _template_trace_file(env, 3)
    assert env["CME213_TRACE_FILE"] == "/tmp/x/t-3.jsonl"
    env2 = {"CME213_TRACE_FILE": "/tmp/x/flat.jsonl"}
    _template_trace_file(env2, 3)  # no placeholder: untouched
    assert env2["CME213_TRACE_FILE"] == "/tmp/x/flat.jsonl"
    _template_trace_file({}, 0)  # no sink configured: no-op


# ----------------------------------------------------------------- metrics

def test_metrics_counter_gauge_histogram():
    metrics.reset()
    metrics.counter("c").inc()
    metrics.counter("c").inc(4)
    metrics.gauge("g").set(13)
    for v in (1.0, 2.0, 3.0, 4.0, 100.0):
        metrics.histogram("h").observe(v)
    snap = metrics.snapshot()
    assert snap["counters"]["c"] == 5
    assert snap["gauges"]["g"] == 13
    h = snap["histograms"]["h"]
    assert h["count"] == 5 and h["min"] == 1.0 and h["max"] == 100.0
    assert h["p50"] == 3.0 and h["sum"] == 110.0
    assert metrics.histogram("h").percentile(0.0) == 1.0


def test_metrics_delta():
    metrics.reset()
    metrics.counter("a").inc(2)
    metrics.histogram("h").observe(1.0)
    before = metrics.snapshot()
    metrics.counter("a").inc(3)
    metrics.counter("b").inc()
    metrics.histogram("h").observe(2.0)
    d = metrics.delta(before, metrics.snapshot())
    assert d["counters"] == {"a": 3, "b": 1}
    assert d["histograms"]["h"]["count_delta"] == 1


def test_histogram_ring_is_bounded():
    metrics.reset()
    h = metrics.histogram("big")
    for i in range(metrics.KEEP + 100):
        h.observe(float(i))
    assert h.count == metrics.KEEP + 100
    assert len(h._recent) == metrics.KEEP


def test_fallback_ladder_updates_metrics():
    from cme213_tpu.core.faults import injected
    from cme213_tpu.core.resilience import with_fallback

    metrics.reset()
    with injected("fail:op.a"):
        res = with_fallback("op", [("a", lambda: 1), ("b", lambda: 2)])
    assert res.rung == "b"
    snap = metrics.snapshot()
    assert snap["counters"]["fallback.demotions"] == 1
    assert snap["counters"]["served.op.b"] == 1
    assert snap["counters"]["faults.fail"] == 1


# ------------------------------------------------------------------ schema

def _record_event_calls():
    pkg_dir = pathlib.Path(cme213_tpu.__file__).parent
    for py in sorted(pkg_dir.rglob("*.py")):
        tree = ast.parse(py.read_text(), filename=str(py))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute) else None)
            if name != "record_event":
                continue
            yield py.relative_to(pkg_dir), node


def test_every_call_site_uses_a_registered_event():
    sites = 0
    for src, node in _record_event_calls():
        assert node.args and isinstance(node.args[0], ast.Constant), (
            f"{src}:{node.lineno}: record_event must be called with a "
            f"literal event name")
        event = node.args[0].value
        assert event in EVENT_SCHEMA, (
            f"{src}:{node.lineno}: event {event!r} not in EVENT_SCHEMA — "
            f"register its required fields in core/trace.py")
        sites += 1
    assert sites >= 15  # the wiring exists (spans + 4 layers)


def test_call_sites_emit_their_documented_fields():
    auto = {"pid", "rank", "incarnation"}
    for src, node in _record_event_calls():
        event = node.args[0].value
        kw = [k.arg for k in node.keywords]
        if None in kw:  # **expansion: covered by the runtime check below
            continue
        missing = set(EVENT_SCHEMA[event]) - set(kw) - auto
        assert not missing, (
            f"{src}:{node.lineno}: {event!r} missing documented "
            f"field(s) {sorted(missing)}")


def test_runtime_records_validate_against_schema():
    """Dynamic call sites (**kwargs) checked by actually driving them."""
    from cme213_tpu.core.faults import injected
    from cme213_tpu.core.resilience import RetryPolicy, with_fallback

    with injected("fail:rt.a"):
        with_fallback("rt", [("a", lambda: 1), ("b", lambda: 2)],
                      policy=RetryPolicy(max_retries=0))
    with span("s", kernel="k"):
        pass
    for rec in trace.events():
        assert validate_record(rec) == [], rec


def test_validate_record_reports_missing():
    assert validate_record({"event": "served", "op": "x"}) == [
        "rung", "demoted", "failed_rungs"]
    assert validate_record({"event": "unknown-event"}) == []


# --------------------------------------------------------------------- CLI

def _write_trace(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def _gang_fixture(tmp_path):
    """Synthetic 2-rank + launcher trace triple shaped like a rankkill
    faultcheck run."""
    base = {"pid": 1, "incarnation": 0}
    launcher = [
        {"event": "gang-launch", "t": 0.0, "rank": None, "incarnation": 0,
         "world": 2, "coordinator": "127.0.0.1:1", "pid": 9},
        {"event": "rank-failed", "t": 3.0, "rank": 1, "incarnation": 0,
         "reason": "exit", "code": 113, "pid": 9},
        {"event": "gang-restart", "t": 3.1, "rank": None, "incarnation": 1,
         "reason": "exit", "pid": 9},
        {"event": "gang-launch", "t": 3.2, "rank": None, "incarnation": 1,
         "world": 2, "coordinator": "127.0.0.1:2", "pid": 9},
        {"event": "gang-exit", "t": 9.0, "rank": None, "incarnation": 1,
         "rc": 0, "pid": 9},
    ]
    r0 = [
        {"event": "heartbeat", "t": 1.0, "rank": 0, "step": 0, **base},
        {"event": "epoch-commit", "t": 2.0, "rank": 0, "epoch": 1,
         "step": 2, "world": 2, "shards": 2, "ms": 5.0, **base},
        {"event": "epoch-commit", "t": 2.5, "rank": 0, "epoch": 2,
         "step": 4, "world": 2, "shards": 2, "ms": 7.0, **base},
        {"event": "commit-loaded", "t": 4.0, "rank": 0, "epoch": 2,
         "step": 4, "candidate": "COMMIT", "pid": 2, "incarnation": 1},
        {"event": "epoch-commit", "t": 5.0, "rank": 0, "epoch": 3,
         "step": 8, "world": 2, "shards": 2, "ms": 6.0, "pid": 2,
         "incarnation": 1},
        {"event": "span-begin", "t": 0.5, "rank": 0, "span": "solve",
         "id": "a.1", "parent": None, **base},
        {"event": "span-end", "t": 6.0, "rank": 0, "span": "solve",
         "id": "a.1", "parent": None, "ms": 5500.0, "pid": 2,
         "incarnation": 1},
    ]
    r1 = [
        {"event": "heartbeat", "t": 1.1, "rank": 1, "step": 0, **base},
        {"event": "fault-injected", "t": 2.9, "rank": 1, "kind": "rankkill",
         "op": "1", "step": 1, **base},
    ]
    paths = []
    for name, recs in (("trace-main.jsonl", launcher),
                       ("trace-0.jsonl", r0), ("trace-1.jsonl", r1)):
        p = tmp_path / name
        _write_trace(p, recs)
        paths.append(str(p))
    return paths


def test_cli_summary_reconstructs_gang_view(tmp_path, capsys):
    paths = _gang_fixture(tmp_path)
    assert trace_cli.main(["summary", *paths]) == 0
    out = capsys.readouterr().out
    assert "ranks: main, r0, r1" in out
    assert "epoch commits: 3" in out and "p50=6.00" in out
    assert "resume: epoch 2, step 4 from COMMIT" in out
    assert "gang: 2 launch(es), 1 verdict(s) [exit], 1 restart(s), " \
           "final rc 0" in out
    assert "rankkill x1" in out


def test_cli_summary_require_missing_span(tmp_path, capsys):
    paths = _gang_fixture(tmp_path)
    assert trace_cli.main(["summary", *paths, "--require", "solve"]) == 0
    assert trace_cli.main(
        ["summary", *paths, "--require", "solve,absent-span"]) == 1
    assert "absent-span" in capsys.readouterr().err


def test_cli_timeline_orders_ranks_chronologically(tmp_path, capsys):
    paths = _gang_fixture(tmp_path)
    assert trace_cli.main(["merge", "--timeline", *paths]) == 0
    out = capsys.readouterr().out
    lines = out.splitlines()
    order = [line.split()[2] for line in lines]  # rank labels
    assert order[0] == "main"  # gang-launch first
    # the verdict chain appears in causal order across files
    joined = "\n".join(lines)
    assert joined.index("fault-injected") < joined.index("rank-failed") \
        < joined.index("gang-restart") < joined.index("commit-loaded") \
        < joined.index("gang-exit")
    # span-begin folded away; span-end visible with its duration
    assert "span-begin" not in joined and "solve ms=5500.0" in joined


def test_cli_merge_emits_sorted_jsonl(tmp_path, capsys):
    paths = _gang_fixture(tmp_path)
    out_path = tmp_path / "merged.jsonl"
    assert trace_cli.main(["merge", *paths, "--out", str(out_path)]) == 0
    recs = [json.loads(line)
            for line in out_path.read_text().splitlines()]
    assert len(recs) == 14
    assert [r["t"] for r in recs] == sorted(r["t"] for r in recs)
    assert all("_file" not in r for r in recs)


def test_cli_parse_error_is_fatal(tmp_path, capsys):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"event": "heartbeat", "t": 1.0}\nnot json\n')
    assert trace_cli.main(["summary", str(p)]) == 2
    assert "bad.jsonl:2" in capsys.readouterr().err


def test_cli_summary_counts_schema_violations(tmp_path, capsys):
    p = tmp_path / "t.jsonl"
    _write_trace(p, [{"event": "served", "t": 1.0, "op": "x", "rung": "a",
                      "demoted": False}])
    assert trace_cli.main(["summary", str(p)]) == 0
    assert "served: missing failed_rungs x1" in capsys.readouterr().out


# ------------------------------------------------------------- integration

def test_spmv_demotion_flows_to_trace_file(tmp_path, monkeypatch, capsys):
    """End-to-end: fault-injected dispatch -> per-process sink -> CLI."""
    from cme213_tpu.apps import spmv_scan as sp
    from cme213_tpu.core.faults import injected

    path = tmp_path / "t.jsonl"
    monkeypatch.setenv(trace.TRACE_FILE_ENV, str(path))
    prob = sp.generate_problem(512, 8, 7, iters=3, seed=0)
    with injected("fail:spmv_scan.pallas-fused"):
        sp.run_spmv_scan(prob, kernel="pallas-fused")
    trace.flush_sink()
    monkeypatch.delenv(trace.TRACE_FILE_ENV)
    capsys.readouterr()
    assert trace_cli.main(
        ["summary", str(path),
         "--require", "spmv_scan.compile,spmv_scan.run"]) == 0
    out = capsys.readouterr().out
    assert "spmv_scan: blocked x1" in out
    assert "spmv_scan.pallas-fused x1" in out
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    assert all(validate_record(r) == [] for r in recs)
    run_end = [r for r in recs if r["event"] == "span-end"
               and r["span"] == "spmv_scan.run"]
    assert run_end and run_end[0]["kernel"] == "blocked"
