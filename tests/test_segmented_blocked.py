"""Blocked O(n) segmented scan (ISSUE 1 tentpole) — correctness across
segment layouts, agreement with the flat log-sweep, and the size-threshold
dispatch behind ``segmented_scan``.

Tolerance model: the blocked form associates additions differently from
the flat sweep (reset-by-subtraction within blocks + cross-block carries),
so float results agree to rounding, not ULP — the model documented in
``ops/segmented_pallas.py``.  On integer-valued inputs every partial sum
is exact, so flat and blocked must agree BITWISE.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from cme213_tpu.ops.segmented import (
    BLOCKED_SCAN_THRESHOLD,
    head_flags_from_starts,
    segmented_scan,
    segmented_scan_blocked,
    segmented_scan_flat,
)
from cme213_tpu.verify import golden


def _run_blocked(v, s, block_size):
    n = v.shape[0]
    flags = head_flags_from_starts(jnp.asarray(s, jnp.int32), n)
    return np.asarray(segmented_scan_blocked(jnp.asarray(v), flags,
                                             block_size=block_size))


def _check(v, s, block_size):
    ref = golden.host_segmented_scan(v, s)
    out = _run_blocked(v, s, block_size)
    scale = max(1.0, float(np.abs(ref).max()))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5 * scale)


@pytest.mark.parametrize("block_size", [64, 256])
def test_random_layout_matches_golden(block_size):
    rng = np.random.default_rng(0)
    n = 2048
    s = np.concatenate(
        [[0], np.sort(rng.choice(np.arange(1, n), 63, replace=False))]
    ).astype(np.int32)
    _check(rng.standard_normal(n).astype(np.float32), s, block_size)


def test_head_on_block_boundary():
    # heads exactly at block boundaries (and one mid-block): the carry
    # must reset precisely at the boundary element, not one off
    n, bs = 1024, 128
    rng = np.random.default_rng(1)
    s = np.array([0, 128, 256, 300, 512, 896], dtype=np.int32)
    _check(rng.standard_normal(n).astype(np.float32), s, bs)


def test_one_giant_segment_threads_carry_through_every_block():
    n, bs = 4096, 64
    v = np.ones(n, dtype=np.float32)
    s = np.array([0], dtype=np.int32)
    out = _run_blocked(v, s, bs)
    np.testing.assert_allclose(out, np.arange(1, n + 1, dtype=np.float32))


def test_all_singleton_segments_identity():
    # every segment length 1 → the scan is the identity.  The blocked
    # form computes it as cumsum[i] − cumsum[i−1], exact only when the
    # partial sums are exact — bitwise on integer-valued data, rounding-
    # tolerance on general floats (the documented tolerance model).
    n, bs = 512, 64
    rng = np.random.default_rng(2)
    s = np.arange(n, dtype=np.int32)
    vi = rng.integers(-100, 100, n).astype(np.float32)
    np.testing.assert_array_equal(_run_blocked(vi, s, bs), vi)
    vf = rng.standard_normal(n).astype(np.float32)
    np.testing.assert_allclose(_run_blocked(vf, s, bs), vf,
                               rtol=1e-5, atol=1e-4)


@pytest.mark.parametrize("n", [100, 4097, 5000])
def test_non_multiple_of_block_n(n):
    # the internal pad must stay quarantined in its own segment
    rng = np.random.default_rng(3)
    p = max(3, n // 50)
    s = np.concatenate(
        [[0], np.sort(rng.choice(np.arange(1, n), p - 1, replace=False))]
    ).astype(np.int32)
    _check(rng.standard_normal(n).astype(np.float32), s, 256)


def test_flat_vs_blocked_bitwise_on_integer_values():
    # integer-valued f32: all sums exact → association is irrelevant and
    # the two kernels must agree to the bit
    rng = np.random.default_rng(4)
    n = 3000
    v = rng.integers(-8, 8, n).astype(np.float32)
    s = np.concatenate(
        [[0], np.sort(rng.choice(np.arange(1, n), 29, replace=False))]
    ).astype(np.int32)
    flags = head_flags_from_starts(jnp.asarray(s), n)
    a = np.asarray(segmented_scan_flat(jnp.asarray(v), flags))
    b = np.asarray(segmented_scan_blocked(jnp.asarray(v), flags,
                                          block_size=128))
    np.testing.assert_array_equal(a, b)


def test_auto_dispatch_small_n_is_bitwise_flat():
    # below the threshold the dispatcher must BE the flat kernel (bitwise):
    # existing small-shape callers rely on unchanged rounding
    rng = np.random.default_rng(5)
    n = 777
    assert n < BLOCKED_SCAN_THRESHOLD
    v = rng.standard_normal(n).astype(np.float32)
    s = np.array([0, 100, 300], dtype=np.int32)
    flags = head_flags_from_starts(jnp.asarray(s), n)
    np.testing.assert_array_equal(
        np.asarray(segmented_scan(jnp.asarray(v), flags)),
        np.asarray(segmented_scan_flat(jnp.asarray(v), flags)))


def test_auto_dispatch_large_n_matches_golden():
    n = BLOCKED_SCAN_THRESHOLD  # smallest size routed to the blocked form
    rng = np.random.default_rng(6)
    v = rng.standard_normal(n).astype(np.float32)
    s = np.concatenate(
        [[0], np.sort(rng.choice(np.arange(1, n), 99, replace=False))]
    ).astype(np.int32)
    flags = head_flags_from_starts(jnp.asarray(s), n)
    out = np.asarray(segmented_scan(jnp.asarray(v), flags))
    ref = golden.host_segmented_scan(v, s)
    scale = max(1.0, float(np.abs(ref).max()))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5 * scale)


def test_blocked_f64():
    rng = np.random.default_rng(7)
    n = 2000
    v = rng.standard_normal(n)  # f64 via x64 disabled → downcast? keep f32
    v = v.astype(np.float32)
    s = np.array([0, 1, 2, 1999], dtype=np.int32)  # singleton-heavy layout
    _check(v, s, 256)


# ------------------------------------------------- engine-level kernels

def test_spmv_blocked_kernel_matches_flat():
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(20_000, 300, 299, iters=5, seed=21)
    out_flat = sp.run_spmv_scan(prob, kernel="flat")
    out_blocked = sp.run_spmv_scan(prob, kernel="blocked")
    scale = max(1.0, float(np.abs(out_flat).max()))
    np.testing.assert_allclose(out_blocked, out_flat, rtol=1e-4,
                               atol=1e-5 * scale)


def test_spmv_pallas_unfused_matches_fused():
    from cme213_tpu.apps import spmv_scan as sp

    prob = sp.generate_problem(3000, 40, 39, iters=4, seed=22)
    fused = sp.run_spmv_scan(prob, kernel="pallas-fused")
    unfused = sp.run_spmv_scan(prob, kernel="pallas")
    scale = max(1.0, float(np.abs(fused).max()))
    np.testing.assert_allclose(unfused, fused, rtol=1e-4, atol=1e-5 * scale)


def test_spmv_bytes_moved_accounting():
    from cme213_tpu.apps.spmv_scan import bytes_moved

    # per iteration: read a + read xx (elem each) + read int32 flags +
    # write a — the single-pass useful-byte convention
    assert bytes_moved(1000, 1) == 1000 * 16
    assert bytes_moved(1000, 7) == 7 * 1000 * 16
    assert bytes_moved(1000, 2, elem=8) == 2 * 1000 * 28


def test_spmv_scan_sweep_quick():
    from cme213_tpu.bench.sweeps import spmv_scan_sweep

    rows = spmv_scan_sweep(ns=(4096,), iters=2, kernels=("flat", "blocked"))
    assert [r["kernel"] for r in rows] == ["flat", "blocked"]
    assert all(r["gbs"] > 0 and not r["error"] for r in rows)
    assert all(float(r["rel_l2"]) < 1e-4 for r in rows)


def test_banked_rows_filtered_by_dtype(tmp_path, monkeypatch):
    """f32 device rows must not surface as banked evidence in the f64
    bench output (ADVICE r5); pre-dtype rows read as f32."""
    import json
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    import bench

    results = tmp_path / "bench_results"
    results.mkdir()
    rows = {
        "tranche1_xla.json":           # legacy row, no dtype field → f32
            {"kernel": "xla", "ok": True, "platform": "tpu", "gbs": 50.85},
        "tranche1_pipeline-k4.json":   # tagged f32
            {"kernel": "pipeline-k4", "ok": True, "platform": "tpu",
             "dtype": "f32", "gbs": 251.8},
        "tranche1_xla_f64.json":       # tagged f64
            {"kernel": "xla", "ok": True, "platform": "tpu",
             "dtype": "f64", "gbs": 25.0},
    }
    for name, row in rows.items():
        (results / name).write_text(json.dumps(row))
    monkeypatch.setattr(bench.os.path, "dirname", lambda p: str(tmp_path))

    f32 = bench._banked_rows("f32")
    assert {r["kernel"] for r in f32} == {"xla", "pipeline-k4"}
    assert all(r.get("dtype", "f32") == "f32" for r in f32)
    f64 = bench._banked_rows("f64")
    assert [r["gbs"] for r in f64] == [25.0]
