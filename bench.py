"""Headline benchmark: hw2-class 2-D heat stencil, order 8, 4000×4000, f32.

Mirrors the reference's measurement: 1000-iteration hot loop, effective
bandwidth = (1 read + 1 write) × 4 B × nx × ny per iteration (the accounting
behind ``hw/hw2/programming/data/data.ods``; see BASELINE.md).  Baseline to
beat: shared-memory order-8 kernel at 4000² on a GTX 580 = **23.97 GB/s**.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Extra per-phase detail goes to stderr.
"""

import json
import sys
import time

BASELINE_GBS = 23.97  # hw2 shared-memory order-8 4000² float (BASELINE.md)


def main() -> None:
    import jax
    import jax.numpy as jnp

    from cme213_tpu.config import SimParams
    from cme213_tpu.grid import make_initial_grid
    from cme213_tpu.ops import run_heat

    nx = ny = 4000
    order = 8
    iters_timed = 200

    params = SimParams(nx=nx, ny=ny, order=order, iters=1000)
    u0 = make_initial_grid(params, dtype=jnp.float32)
    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr)

    u = jax.device_put(u0, dev)
    # warmup / compile (runs a short loop of the same traced program)
    w = run_heat(u, 10, order, params.xcfl, params.ycfl)
    w.block_until_ready()

    u = jax.device_put(u0, dev)
    start = time.perf_counter()
    out = run_heat(u, iters_timed, order, params.xcfl, params.ycfl)
    out.block_until_ready()
    elapsed = time.perf_counter() - start

    ms_per_iter = elapsed * 1e3 / iters_timed
    bytes_per_iter = 2 * 4 * nx * ny          # read prev + write next, f32
    gbs = bytes_per_iter / (elapsed / iters_timed) / 1e9
    # order-8 per point: 2 axes × (9 mul + 8 add) + center combine (2 mul,
    # 2 add) = 38 flops
    flops_per_iter = 38 * nx * ny
    gfs = flops_per_iter / (elapsed / iters_timed) / 1e9
    print(f"{ms_per_iter:.3f} ms/iter, {gbs:.2f} GB/s eff, {gfs:.2f} GF/s",
          file=sys.stderr)

    print(json.dumps({
        "metric": "heat2d stencil order-8 4000x4000 f32 effective bandwidth",
        "value": round(gbs, 2),
        "unit": "GB/s",
        "vs_baseline": round(gbs / BASELINE_GBS, 3),
    }))


if __name__ == "__main__":
    main()
